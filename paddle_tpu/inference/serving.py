"""Continuous-batching LLM serving engine over paged KV caches.

Reference role: the serving layer PaddleNLP/FastDeploy put on top of
Paddle Inference (dynamic batching + paged/ragged KV attention for mixed-
length streams; reference mount empty, no cites — SURVEY.md §2.1
inference row, PAPERS.md ragged-paged-attention).

TPU-native design — the vLLM recipe restructured for XLA's static-shape
world. Two engine modes share the pool/slot machinery:

**Unified mode (default, ``unified=True``)** — ONE compiled
batching-step program for the whole scheduler turn, built on the ragged
paged-attention entry point (PAPERS.md "Ragged Paged Attention"): a
mixed ragged pass advances every slot — prefill slots stream their next
``prefill_chunk`` prompt tokens, active decode slots ride their pending
token as a length-1 sequence, idle slots are length 0 — through one
``[num_slots, prefill_chunk]`` forward, samples where a prompt
completes or a decode step fires, then chains ``decode_chunk - 1``
in-program decode micro-steps via ``lax.scan``. Prefill→decode
transition happens ON DEVICE inside the program (a slot whose prompt
ends in the mixed pass decodes from micro-step 1), so the PR-3
prefill-wave/decode-chunk interleave, its first-token echo machinery,
and the residual compiled-signature zoo all collapse: steady-state
``compiled_programs`` == 1.

**Legacy mode (``unified=False``)** — the PR-3 two-program-family
engine (batched prefill waves interleaved with adaptive decode chunks),
kept as the scheduling-parity oracle for the ``serving_parity`` CI gate
and for A/B benching.

Shared structure:

- The KV cache is a global PAGE POOL per layer ([KVH, num_pages,
  page_size, D]); each admitted request owns a page list (its block
  table row). Page 0 is a reserved trash page for drained slots.
- PREFIX CACHE (ISSUE 12, default on): completed prefills publish
  their full prompt pages into a radix index keyed by token blocks at
  ``page_size`` granularity; an admitted request whose prompt prefix
  is resident ATTACHES the existing physical pages (refcounted,
  read-shared) and chunk-prefills only its unseen suffix — a fully-
  cached prompt COW-forks the last shared page to recompute its final
  token's logits. Eviction is refcount-aware LRU over unreferenced
  cache pages, composed with the deferred-free discipline below; the
  ``PADDLE_TPU_SERVING_AUDIT`` invariant extends to shared pages
  (free + private + cache + deferred + trash == num_pages, refcounts
  exact).
- A fixed number of SLOTS (the batch dimension) keeps every compiled
  shape static. Admission = host-side: allocate pages from the free
  list and mark the slot PREFILLING.
- Prefill is CHUNKED and BATCHED through the paged pool: ONE compiled
  prefill signature ([num_slots, prefill_chunk] ids) advances every
  prefilling slot ``prefill_chunk`` prompt tokens per program — k/v are
  written into the slot's pages incrementally
  (``ops.paged_attention.paged_prefill_write``) and the chunk's queries
  attend causally over the paged history
  (``paged_prefill_attention``). No per-bucket dense-cache forward, no
  exact-length recompiles for prompts longer than every bucket: every
  prompt length flows through the same program, and up to
  ``admit_batch`` queued prompts ride one program together. Prefill
  waves INTERLEAVE with decode chunks, so a long prompt no longer
  stalls active decode streams.
- Decoding runs in compiled CHUNKS: ONE program advances ALL active
  slots ``n`` tokens via a ``lax.scan`` (per-slot positions, paged
  attention reads, trash-page-guarded writes). The chunk length is
  ADAPTIVE (``adaptive_chunk``): clamped to the minimum remaining token
  budget across active slots (quantized to a power-of-two ladder under
  ``decode_chunk`` to bound compiled signatures), so a drain wave ends
  exactly at the chunk boundary — no overshoot slot-steps, and the
  once-per-drain-wave wasted speculative chunk program is gone (the
  host can prove the successor would do no work).
- Between chunks the host scheduler drains finished slots (eos or token
  budget), frees their pages, and admits queued requests into the freed
  slots — mixed-length streams flow through without ever reshaping the
  compiled programs.
- Hot state (last token / context length / active mask / RNG key / page
  pools) is DEVICE-RESIDENT between programs: prefill waves and decode
  chunks chain device state asynchronously; each decode chunk fetches
  one packed int32 array (emitted tokens + first-token echoes + ctx/
  active mirrors), and prefill never fetches — a prompt's first token
  lands in device state and is echoed through the next chunk's packed
  fetch. Measured on the tunnel (v5e): per-call overhead was ~0.5s with
  per-array uploads + a blocking scalar fetch per admission; round
  trips, not kernels, set the serving throughput.
- Per-request latency accounting rides the scheduler: TTFT (arrival →
  first token on host) and smoothed inter-token latency, exposed as
  p50/p99 gauges next to the occupancy/overlap counters from PR 2, plus
  a compiled-signature counter (``compiled_programs``) that the
  compile-budget CI gate asserts on.
"""

from __future__ import annotations

import time
import zlib
from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, no_grad
from ..profiler import flight_recorder as _frec
from ..profiler import metrics as _pmetrics
from ..profiler.trace import get_trace_log as _get_trace_log
from .reliability import (MAX_HOPS as _MAX_HOPS, DeadlineExceeded,
                          RequestCancelled, RequestQuarantined,
                          record_hop)

__all__ = ["ContinuousBatchingEngine", "ServedRequest",
           "record_hop", "request_trace_summary"]

# the serving metric vocabulary (docs/observability.md table;
# tools/check_metric_names.py lints these literals). Each engine owns
# a PRIVATE MetricsRegistry instance of these — two engines in one
# process never cross-pollute.
_pmetrics.declare("serving/chunks", "counter",
                  "compiled programs dispatched (unified steps + legacy "
                  "decode chunks)")
_pmetrics.declare("serving/chunk_slot_steps", "counter",
                  "slot-steps dispatched (num_slots x chunk length, "
                  "active or not)")
_pmetrics.declare("serving/active_slot_steps", "counter",
                  "slot-steps belonging to slots that could advance at "
                  "dispatch")
_pmetrics.declare("serving/tokens_emitted", "counter",
                  "generated tokens delivered to requests")
_pmetrics.declare("serving/prefills", "counter",
                  "requests admitted into a slot")
_pmetrics.declare("serving/prefills_overlapped", "counter",
                  "admissions made while a compiled program was in "
                  "flight (overlap pipeline)")
_pmetrics.declare("serving/prefill_waves", "counter",
                  "programs that carried prompt tokens")
_pmetrics.declare("serving/chunks_empty", "counter",
                  "harvested programs that delivered no tokens "
                  "(unpredictable eos stops)")
_pmetrics.declare("serving/unified_steps", "counter",
                  "unified batching-step programs dispatched (0 in "
                  "legacy mode)")
_pmetrics.declare("serving/requests_completed", "counter",
                  "requests finished (eos or length)")
_pmetrics.declare("serving/run_seconds", "counter",
                  "wall seconds spent inside run()")
_pmetrics.declare("serving/ttft_ms", "histogram",
                  "request arrival -> first token on host, ms (bounded "
                  "reservoir; p50/p99 exposed via gauges())")
_pmetrics.declare("serving/itl_ms", "histogram",
                  "smoothed inter-token latency per request with >=2 "
                  "tokens, ms (bounded reservoir)")
_pmetrics.declare("obs/overhead_frac", "gauge",
                  "fraction of run() wall time spent inside "
                  "observability instrumentation, self-measured — "
                  "per-engine on its private registry, fleet-tier on "
                  "the federated registry (the <2% pinned contract)")
# ISSUE 10 reliability vocabulary: overload is a first-class mode, so
# its economics are first-class metrics
_pmetrics.declare("serving/preempt_evictions", "counter",
                  "active sequences evicted on page exhaustion and "
                  "requeued for recompute-style re-prefill")
_pmetrics.declare("serving/preempt_pages_reclaimed", "counter",
                  "KV pages reclaimed by preemption evictions")
_pmetrics.declare("serving/preempt_recompute_tokens", "counter",
                  "previously generated tokens re-prefilled when a "
                  "preempted request was re-admitted")
_pmetrics.declare("serving/requests_cancelled", "counter",
                  "requests completed with RequestCancelled")
_pmetrics.declare("serving/deadline_ttft_expired", "counter",
                  "requests that missed their TTFT deadline before "
                  "producing a first token")
_pmetrics.declare("serving/deadline_total_expired", "counter",
                  "requests that exceeded their total deadline "
                  "(mid-stream or queued)")
_pmetrics.declare("serving/quarantined", "counter",
                  "requests completed with RequestQuarantined after "
                  "repeated step-failure implication")
_pmetrics.declare("serving/containments", "counter",
                  "step-level fault containments (a failed compiled "
                  "step converted to slot/page reset + requeue instead "
                  "of engine death)")
_pmetrics.declare("serving/shed_rejections", "counter",
                  "submissions rejected at the admission door "
                  "(Overloaded, with a computed retry-after)")
_pmetrics.declare("serving/shed_retry_after_s", "gauge",
                  "retry-after seconds attached to the most recent "
                  "Overloaded rejection")
# ISSUE 19 pressure gauges: the LIVE signals the autoscaler and
# /statusz read — the counters above are monotonic history, these are
# "now" (set per gauge emission, and per fleet turn on fleet replicas)
_pmetrics.declare("serving/queue_depth", "gauge",
                  "requests currently waiting in the admission queue "
                  "(not yet in a slot)")
_pmetrics.declare("serving/shed_rate", "gauge",
                  "admission sheds per second over the controller's "
                  "trailing window (AdmissionController.shed_rate)")
# ISSUE 12 prefix-cache vocabulary: shared-prefix reuse is the serving
# capacity story, so its economics are first-class metrics
_pmetrics.declare("serving/prefix_cache_hits", "counter",
                  "admissions that attached >=1 cached prefix page "
                  "(suffix-only prefill)")
_pmetrics.declare("serving/prefix_cache_misses", "counter",
                  "admissions that found no cached prefix page")
_pmetrics.declare("serving/prefix_cache_tokens_saved", "counter",
                  "prompt tokens whose prefill was skipped by "
                  "attaching cached prefix pages")
_pmetrics.declare("serving/prefix_cache_evictions", "counter",
                  "unreferenced cache pages reclaimed by the "
                  "refcount-aware LRU under allocation pressure")
_pmetrics.declare("serving/prefix_cache_cow_forks", "counter",
                  "copy-on-write page forks (a sequence had to write "
                  "into a fully-shared page)")
_pmetrics.declare("serving/prefix_cache_pages", "gauge",
                  "physical pages currently owned by the prefix-cache "
                  "radix index (referenced + evictable)")

# -- disaggregated prefill/decode: engine-side migration counters (ISSUE 17)
_pmetrics.declare("disagg/migrated_out", "counter",
                  "requests a prefill-role engine exported to a decode "
                  "replica after sampling their first token")
_pmetrics.declare("disagg/kv_pages_exported", "counter",
                  "full prompt-KV pages serialized into migration "
                  "payloads (per-pool crc32-checksummed)")
_pmetrics.declare("disagg/kv_imported_pages", "counter",
                  "migrated KV pages written into the destination "
                  "engine's pools and seeded into its prefix-cache "
                  "radix index")
_pmetrics.declare("disagg/kv_import_dedup_pages", "counter",
                  "migrated KV pages already resident at the "
                  "destination (idempotent re-delivery or shared "
                  "prefix) — skipped, not rewritten")
_pmetrics.declare("disagg/kv_import_crc_rejects", "counter",
                  "migrated KV page blocks rejected at import "
                  "(checksum mismatch or malformed payload); the "
                  "request still replays correctly from its prompt")

# -- quantized serving: pool geometry gauges (ISSUE 20)
_pmetrics.declare("serving/kv_quant_bits", "gauge",
                  "bits per stored KV element in the page pools "
                  "(16 = bf16/f32 full precision, 8 = int8/fp8 "
                  "quantized)")
_pmetrics.declare("serving/kv_quant_pool_bytes", "gauge",
                  "total bytes of the KV DATA page pools across all "
                  "layers (the capacity denominator quantization "
                  "shrinks)")
_pmetrics.declare("serving/kv_quant_scale_pool_bytes", "gauge",
                  "total bytes of the page-parallel f32 scales pools "
                  "(0 when kv_quant='none') — the quantization "
                  "overhead term in the capacity math")

# -- speculative decoding: draft/verify economics (ISSUE 18)
_pmetrics.declare("spec/steps", "counter",
                  "speculative unified-step programs dispatched "
                  "(draft + ragged verify in one compiled step)")
_pmetrics.declare("spec/tokens_drafted", "counter",
                  "draft tokens fed into verification chunks")
_pmetrics.declare("spec/tokens_accepted", "counter",
                  "draft tokens the target distribution accepted "
                  "(committed in place, ctx advanced over their KV)")
_pmetrics.declare("spec/tokens_rejected", "counter",
                  "draft tokens rejected at verification and rolled "
                  "back (their in-flight KV writes are left "
                  "unreachable behind ctx and overwritten in place)")

#: the historical ``_stats`` key set, preserved verbatim — now backed
#: by ``serving/*`` registry counters
_STAT_KEYS = ("chunks", "chunk_slot_steps", "active_slot_steps",
              "tokens_emitted", "prefills", "prefills_overlapped",
              "prefill_waves", "chunks_empty", "unified_steps",
              "requests_completed", "run_seconds",
              # ISSUE-10 reliability counters ride the same view so
              # reset_gauges()/as_dict() cover them uniformly
              "preempt_evictions", "preempt_pages_reclaimed",
              "preempt_recompute_tokens", "requests_cancelled",
              "deadline_ttft_expired", "deadline_total_expired",
              "quarantined", "containments", "shed_rejections",
              # ISSUE-12 prefix-cache counters
              "prefix_cache_hits", "prefix_cache_misses",
              "prefix_cache_tokens_saved", "prefix_cache_evictions",
              "prefix_cache_cow_forks")


class _StatsView:
    """Dict-shaped view over the engine's registry counters: the
    ``_stats`` surface predates the metrics registry and tests index
    it (``eng._stats["active_slot_steps"]``), so the migration keeps
    the mapping protocol while the registry holds the truth."""

    __slots__ = ("_c",)

    def __init__(self, registry):
        self._c = {k: registry.counter("serving/" + k)
                   for k in _STAT_KEYS}

    def __getitem__(self, k):
        return self._c[k].value

    def __setitem__(self, k, v):
        self._c[k].set(v)

    def inc(self, k, n=1):
        self._c[k].inc(n)

    def __iter__(self):
        return iter(self._c)

    def keys(self):
        return self._c.keys()

    def as_dict(self):
        return {k: c.value for k, c in self._c.items()}


class _PrefixCacheNode:
    """One cached FULL KV page of a token prefix (ISSUE 12): a node of
    the radix index over prompt-token blocks at ``page_size``
    granularity. The tree position encodes the whole prefix — two
    sequences reach the same node iff their first ``depth *
    page_size`` tokens are identical, so a node's page content
    (KV for those positions) is exact by construction, not
    probabilistic. ``ref`` counts slots currently attached
    (read-sharing the page); 0 means resident-but-evictable. The
    refcount chain is monotone root→leaf (every attachment references
    a contiguous prefix from the root), which is what makes
    leaf-first LRU eviction safe: a ref-0 node's whole subtree is
    ref-0."""

    __slots__ = ("key", "page", "parent", "children", "ref", "stamp")

    def __init__(self, key, page, parent):
        self.key = key          # the page's token block (bytes)
        self.page = page        # physical page id it owns
        self.parent = parent
        self.children = {}      # token-block bytes -> child node
        self.ref = 0            # attached readers (slots)
        self.stamp = 0          # LRU clock (engine _pc_clock)


#: copy-on-write fork: duplicate one physical page across EVERY
#: layer's k/v pool in ONE compiled dispatch (dst becomes a private
#: writable copy of the shared src) — per-pool launches would put
#: 2 x num_layers sequential dispatches on the TTFT-critical
#: admission path.
_pc_copy_page = jax.jit(lambda pools, src, dst:
                        [p.at[:, dst].set(p[:, src]) for p in pools])


#: KV-page import (ISSUE 17): write ALL of a migrated request's
#: accepted pages into every layer's k/v pool in ONE compiled
#: dispatch. ``dst`` is an int32 vector of page indices and each
#: pool's ``data`` stacks the matching page contents along the page
#: axis ([kv_heads, n, page_size, head_dim]) — per-page dispatches put
#: ~2 x num_layers x pages_per_request sequential launches on the
#: migration pump, the pump's dominant cost. The page count per
#: request is bounded by max_len/page_size, so the compile set stays
#: small. Functional update, so the write chains behind every
#: in-flight program in the device stream exactly like the COW fork
#: above — an import never races a dispatched step.
_kv_write_pages = jax.jit(lambda pools, dst, data:
                          [p.at[:, dst].set(d)
                           for p, d in zip(pools, data)])


#: the priority band EXTERNAL requests are clamped into by the HTTP
#: front door (inference/api_server.py): higher wins admission order
#: and may preempt. In-process callers may use any int — the band only
#: bounds what an untrusted client can claim over the wire.
PRIORITY_RANGE = (0, 15)


@dataclass(eq=False)
class ServedRequest:
    request_id: int
    prompt: np.ndarray                 # [S] int
    max_new_tokens: int
    eos_token_id: int | None = None
    tokens: list = field(default_factory=list)   # generated ids
    finished: bool = False
    finish_reason: str | None = None   # "eos" | "length" | "cancelled"
    #                                  # | "deadline" | "quarantined"
    # latency accounting (seconds, perf_counter clock)
    t_arrive: float = 0.0              # add_request
    t_admit: float = 0.0               # admitted into a slot
    t_prefill_done: float = 0.0        # prompt fully streamed
    t_first: float = 0.0               # first token visible host-side
    t_done: float = 0.0                # finished
    #: lifecycle-trace sampling decision (engine trace_sample_rate)
    traced: bool = False
    # ---- lifecycle control (ISSUE 10) --------------------------------
    #: higher wins admission order; a strictly-higher-priority arrival
    #: may preempt running lower-priority sequences for pages/slots
    priority: int = 0
    #: seconds from arrival within which the first token must land
    #: (None = no TTFT deadline)
    ttft_deadline_s: float | None = None
    #: seconds from arrival within which the request must finish
    deadline_s: float | None = None
    #: cancellation requested; honored at the next scheduler turn
    cancelled: bool = False
    #: typed failure (RequestCancelled / DeadlineExceeded /
    #: RequestQuarantined); None for a normal completion
    error: Exception | None = None
    #: times this request was evicted and requeued for recompute
    preemptions: int = 0
    #: containment blame: failed steps this request rode; crossing the
    #: engine's max_strikes quarantines it
    strikes: int = 0
    # ---- fleet-level trace context (ISSUE 13) ------------------------
    #: one trace id per CLIENT request, minted by the fleet router and
    #: shared by every attempt (hedge duplicates, failover replays);
    #: None for a standalone engine (its request_id is the trace)
    trace_id: int | None = None
    #: the cross-replica hop list — admission, preemption/replay,
    #: salvage, failover re-admission, hedge launch, completion — each
    #: hop a small dict {kind, t, replica?, ...}. Hedge copies SHARE
    #: the primary's list object, so the winner and the cancelled
    #: loser interleave into one timeline (bounded; see _hop)
    hops: list = field(default_factory=list)
    #: hops dropped past the bound (a preemption storm must not grow
    #: a request's memory without limit)
    hops_dropped: int = 0
    #: SLO accounting label (profiler/slo.py): attainment windows and
    #: burn-rate alerts partition by tenant
    tenant: str | None = None

    def cancel(self):
        """Request cancellation. Safe from any thread; the engine
        honors it at its next scheduler turn — pages are freed and the
        request completes with ``RequestCancelled`` (tokens already
        emitted are kept)."""
        self.cancelled = True


def request_trace_summary(req) -> dict:
    """The condensed end-to-end trace of a finished request — what the
    :class:`~paddle_tpu.profiler.trace.RequestTraceLog` stores and
    ``/statusz`` renders for the N slowest recent traces. One trace id
    covers every attempt (preemption replays, failover re-admissions,
    the hedge winner AND its cancelled loser all hop into the same
    list)."""
    tid = req.trace_id if req.trace_id is not None else req.request_id
    t0 = req.t_arrive
    hops = list(req.hops or ())
    # overflow is counted IN the shared list (a hedge copy may have
    # been the object that hit the cap — see reliability.record_hop)
    dropped = hops[-1]["dropped"] if hops \
        and hops[-1].get("kind") == "truncated" else req.hops_dropped
    return {
        "trace_id": int(tid),
        "latency_ms": round((req.t_done - t0) * 1e3, 3)
        if req.t_done else 0.0,
        "ttft_ms": round((req.t_first - t0) * 1e3, 3)
        if req.t_first else None,
        "tokens": len(req.tokens),
        "finish_reason": req.finish_reason,
        "error": type(req.error).__name__
        if req.error is not None else None,
        "tenant": req.tenant,
        "priority": int(req.priority),
        "preemptions": int(req.preemptions),
        "hops": [dict(h) for h in hops],
        "hops_dropped": int(dropped),
    }


class ContinuousBatchingEngine:
    """Schedules mixed-length generation streams through ONE compiled
    unified batching-step program (ragged mixed prefill+decode; default)
    or, with ``unified=False``, the legacy prefill-wave/decode-chunk
    pair. Greedy or temperature sampling.

    model: any CausalLM Layer implementing ``forward(ids, caches=, pos=,
    tables=)`` + ``init_kv_cache`` — Llama, Qwen2 (incl. MoE), and GPT2
    all qualify. num_slots is the batch size; total pool memory =
    num_pages * page_size tokens of KV per layer.

    ``prompt_buckets`` is kept for API compatibility: buckets no longer
    select prefill signatures (there is exactly ONE), but the largest
    bucket seeds the default ``prefill_chunk``."""

    def __init__(self, model, num_slots=4, page_size=16, num_pages=None,
                 max_len=512, decode_chunk=None, prompt_buckets=(32, 64, 128),
                 eos_token_id=None, greedy=True, temperature=1.0,
                 seed=0, prefill_chunk=None, admit_batch=None,
                 adaptive_chunk=True, unified=True,
                 trace_sample_rate=0.01, latency_reservoir=2048,
                 max_strikes=2, max_containments=8, audit=None,
                 prefix_cache=None, role="both", spec_decode=False,
                 spec_k=None, spec_draft=None, kv_quant="none"):
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"unknown engine role {role!r}")
        if kv_quant not in ("none", "int8", "fp8"):
            raise ValueError(f"unknown kv_quant {kv_quant!r} "
                             "(expected 'none', 'int8' or 'fp8')")
        if kv_quant == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
            raise ValueError(
                "kv_quant='fp8' needs jax.numpy.float8_e4m3fn, which "
                "this backend does not provide — use 'int8'")
        self.kv_quant = kv_quant
        # disaggregation role (ISSUE 17): a "prefill" engine runs
        # chunked prefill to completion, samples the first token, then
        # EXPORTS the finished full KV pages + request state into
        # ``migrations_out`` instead of decoding — the router moves the
        # record to a decode-capable engine, where import_migration()
        # seeds the prefix cache and replays through the recompute
        # path. "decode"/"both" engines behave identically at this
        # layer (a decode engine can still prefill — that IS the
        # cross-role failover path); the role only changes routing
        # preference and the prefill engine's drain behavior.
        self.role = role
        self.model = model
        cfg = model.config
        self.cfg = cfg
        # weight-only serving quantization (ISSUE 20): a config with
        # weight_quant set gets its big projections converted to
        # dequant-in-matmul form once, at engine construction
        # (quantize_for_serving is idempotent — a pre-converted model
        # or a second engine over the same model is a no-op)
        if getattr(cfg, "weight_quant", None):
            from ..nn.quant import quantize_for_serving
            quantize_for_serving(model)
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pages_per_slot = -(-self.max_len // self.page_size)
        # +1: page 0 is the reserved trash page
        self.num_pages = int(num_pages) if num_pages is not None else \
            self.num_slots * self.pages_per_slot + 1
        # also the KV-pool dtype below AND the tuner-cache key's dtype
        # component — one probe so the two can never diverge. First
        # FLOATING param: a weight-quantized model carries int8 buffers
        # whose dtype must not leak into the activation/pool dtype.
        dtype = next(p._data.dtype for p in model.parameters()
                     if jnp.issubdtype(p._data.dtype, jnp.floating))
        # chunk-ladder knobs left as None resolve through the autotuner
        # cache ("serving_chunks" surface, keyed by slots/max_len/page —
        # registered at the bottom of this module), then fall back to
        # the static derivations; an explicit argument always wins
        tuned = {}
        if decode_chunk is None or prefill_chunk is None \
                or admit_batch is None:
            from ..tuner import lookup
            tuned = lookup("serving_chunks",
                           {"slots": self.num_slots,
                            "max_len": self.max_len,
                            "page": self.page_size}, str(dtype)) or {}
        if decode_chunk is None:
            decode_chunk = int(tuned.get("decode_chunk", 0)) or 16
        self.decode_chunk = int(decode_chunk)
        self.adaptive_chunk = bool(adaptive_chunk)
        self.prompt_buckets = tuple(sorted(prompt_buckets)) \
            if prompt_buckets else ()
        if prefill_chunk is None:
            prefill_chunk = int(tuned.get("prefill_chunk", 0)) or \
                (self.prompt_buckets[-1] if self.prompt_buckets else 32)
        self.prefill_chunk = max(1, min(int(prefill_chunk), self.max_len))
        if admit_batch is None:
            admit_batch = int(tuned.get("admit_batch", 0)) or self.num_slots
        self.admit_batch = max(1, min(int(admit_batch), self.num_slots))
        self.eos = -1 if eos_token_id is None else int(eos_token_id)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)

        # MHA models (e.g. GPT2) carry no kv-head/head-dim fields
        kvh = getattr(cfg, "num_key_value_heads",
                      cfg.num_attention_heads)
        d = getattr(cfg, "head_dim",
                    cfg.hidden_size // cfg.num_attention_heads)
        # per layer: (key_pages, value_pages) — flat list like dense
        # caches; geometry kept so step-failure containment can rebuild
        # the pools from scratch (_reset_device_state). Quantized KV
        # (ISSUE 20) interleaves two extra pools per layer — the
        # page-parallel f32 scales pools (key_scales, value_scales),
        # shape (kvh, num_pages, page_size): one scale per (token,
        # kv head), page axis at index 1 like the data pools, so every
        # generic pool operation (COW page copy, migration export/crc,
        # batched import landing pads, containment rebuild) composes
        # over the flat list unchanged.
        self._pool_shape = (kvh, self.num_pages, self.page_size, d)
        self._pool_dtype = dtype if kv_quant == "none" else jnp.dtype(
            jnp.int8 if kv_quant == "int8" else jnp.float8_e4m3fn)
        self._scale_shape = (kvh, self.num_pages, self.page_size)
        if kv_quant == "none":
            self._pool_shapes = [self._pool_shape] * 2
            self._pool_dtypes = [self._pool_dtype] * 2
        else:
            self._pool_shapes = [self._pool_shape, self._pool_shape,
                                 self._scale_shape, self._scale_shape]
            self._pool_dtypes = [self._pool_dtype, self._pool_dtype,
                                 jnp.float32, jnp.float32]
        self._pool_shapes = self._pool_shapes * cfg.num_hidden_layers
        self._pool_dtypes = self._pool_dtypes * cfg.num_hidden_layers
        self._n_pools = len(self._pool_shapes)
        self.pools = [Tensor(jnp.zeros(s, dt)) for s, dt in
                      zip(self._pool_shapes, self._pool_dtypes)]
        # static pool-geometry facts for the kv_quant gauges
        self._kv_quant_bits = 8 * jnp.dtype(self._pool_dtype).itemsize
        self._kv_pool_bytes = sum(
            int(np.prod(s)) * jnp.dtype(dt).itemsize
            for s, dt in zip(self._pool_shapes, self._pool_dtypes)
            if len(s) == 4)
        self._kv_scale_pool_bytes = sum(
            int(np.prod(s)) * jnp.dtype(dt).itemsize
            for s, dt in zip(self._pool_shapes, self._pool_dtypes)
            if len(s) == 3)

        self._free_pages = deque(range(1, self.num_pages))
        # host-side slot bookkeeping (admission decisions, drain)
        B, MP = self.num_slots, self.pages_per_slot
        self.tables = np.zeros((B, MP), np.int32)
        self.ctx = np.zeros((B,), np.int32)       # mirror (packed fetch)
        self.active = np.zeros((B,), bool)        # mirror (packed fetch)
        self.limits = np.zeros((B,), np.int32)    # ctx budget per slot
        self.slot_eos = np.full((B,), -1, np.int32)  # per-request eos
        self.slot_req: list[ServedRequest | None] = [None] * B
        self.slot_pages: list[list] = [[] for _ in range(B)]
        # the ADMISSION prompt per slot: the request's prompt, plus —
        # for a preempted request re-admitted for recompute — every
        # token it had already generated (vLLM recompute preemption:
        # chunked prefill is token-identical to the decode it replays,
        # so the stream continues exactly where the eviction cut it)
        self._slot_prompt: list[np.ndarray | None] = [None] * B
        # chunked-prefill progress: a slot whose prompt is still being
        # streamed into its pages is PREFILLING — inactive for decode,
        # ineligible for drain
        self._prefilling = np.zeros((B,), bool)
        self._prefill_off = np.zeros((B,), np.int32)   # tokens dispatched
        self._act_target = np.zeros((B,), bool)  # activate on completion
        # host prediction of device ctx (exact for length-limited slots;
        # an eos stop only ever makes it an overestimate) — drives the
        # adaptive chunk length and the is-the-successor-worth-it test
        self._pred_ctx = np.zeros((B,), np.int32)
        # monotone program-dispatch counter + per-slot activation seq:
        # a decode chunk dispatched BEFORE a slot's final prefill wave
        # has a stale view of that slot, so its ctx/active mirrors must
        # not be applied at harvest
        self._seq = 0
        self._act_since = np.zeros((B,), np.int64)
        # pending first-token echo: slots whose prefill finished but
        # whose first token has not been appended host-side yet
        self._pending_first = np.zeros((B,), bool)
        # echo snapshotted into a dispatched-but-unharvested chunk: the
        # slot must not drain until that harvest appends the token (a
        # one-shot request admitted mid-stream would otherwise finish
        # empty — its pending flag is cleared at dispatch, but the token
        # only arrives with the chunk's packed fetch)
        self._echo_inflight = np.zeros((B,), bool)

        # device-resident hot state (never round-trips between chunks);
        # admission mutates it with tiny async .at[slot].set dispatches
        self._dev_tok = jnp.zeros((B,), jnp.int32)
        self._dev_ctx = jnp.zeros((B,), jnp.int32)
        self._dev_act = jnp.zeros((B,), bool)
        self._dev_tbl = jnp.zeros((B, MP), jnp.int32)
        self._dev_lim = jnp.zeros((B,), jnp.int32)
        self._dev_eos = jnp.full((B,), -1, jnp.int32)

        self.queue: deque[ServedRequest] = deque()
        self.completed: list[ServedRequest] = []
        # disaggregation (ISSUE 17): exported (request, kv payload)
        # records awaiting router pickup, and — per exported request —
        # the prefix-cache node chain pinned against eviction until
        # the destination acks the import (release_exported); the page
        # audit counts these pins as live attachments
        self.migrations_out: deque = deque()
        self._exported_pins: dict[int, list] = {}
        self._next_id = 0
        self._seed = int(seed)
        self._key = jax.random.PRNGKey(seed)
        # ---- reliability state (ISSUE 10) ----------------------------
        # pages reclaimed from an EVICTED (still device-active) slot are
        # quarantined until every compiled program dispatched before the
        # eviction has been harvested: an in-flight program still writes
        # the old owner's kv through its dispatch-time block table, and
        # handing the pages to a new request in the same turn would
        # interleave two owners' writes. (gate_seq, pages) entries.
        self._deferred_free: list[tuple[int, list]] = []
        self._last_fetch_dispatch_seq = 0   # newest fetched-program seq
        self._last_harvest_seq = 0          # newest harvested seq
        # admission order degrades to plain FIFO (the historical
        # contract) until a non-zero priority is ever seen
        self._has_priorities = False
        # the per-turn reap's O(queue) sweep only runs once lifecycle
        # control (a deadline or an engine-level cancel) is in play —
        # plus a periodic sweep so a direct ServedRequest.cancel() on
        # a QUEUED handle (a plain flag the engine cannot observe
        # eagerly) is still honored within a bounded number of turns
        self._lifecycle_seen = False
        self._reap_turn = 0
        # completions produced OUTSIDE the drain pass (already-complete
        # replays adopted at admission) — drained into the next turn's
        # done list so run()/step() callers still see them
        self._done_pending: list[ServedRequest] = []
        # step-failure containment: blame threshold + containment
        # budget (an engine failing every step escapes to the
        # supervisor instead of looping forever). The budget resets at
        # every run() entry; a bare step() loop spends it until the
        # next run().
        self.max_strikes = int(max_strikes)
        self.max_containments = int(max_containments)
        self._containments_run = 0
        # page-accounting audit (PADDLE_TPU_SERVING_AUDIT=1, on in
        # tests): free + Σ slot pages + deferred + trash == num_pages
        # after every drain/preempt/cancel, so reclamation bugs fail
        # loudly instead of leaking quietly
        from ..profiler import _env_bool
        self._audit = _env_bool("PADDLE_TPU_SERVING_AUDIT") \
            if audit is None else bool(audit)
        # ---- prefix cache (ISSUE 12) ---------------------------------
        # radix index over FULL pages of prompt-token blocks: an
        # admitted request whose prompt prefix is resident attaches
        # the existing physical pages (refcounted, read-shared) and
        # only prefills its unseen suffix. Default ON; the env knob
        # or prefix_cache=False restores exclusive-page behavior.
        self._prefix_cache = _env_bool("PADDLE_TPU_PREFIX_CACHE", True) \
            if prefix_cache is None else bool(prefix_cache)
        self._pc_root = _PrefixCacheNode(None, 0, None)   # sentinel
        self._pc_nodes: dict[int, _PrefixCacheNode] = {}  # page -> node
        self._pc_clock = 0                                # LRU stamps
        #: per-slot attached cache nodes, in table-row order — the
        #: slot's block table is [shared pages..., private pages...]
        self.slot_shared: list[list] = [[] for _ in range(B)]
        self._prefill_fn = None        # legacy: ONE prefill signature
        self._chunk_fns = {}           # legacy: chunk len -> program
        self._compiled = set()         # distinct compiled signatures
        # unified mode: ONE batching-step program (mixed ragged pass +
        # decode_chunk-1 in-program decode micro-steps); per-slot count
        # of dispatched-but-unharvested steps that may emit tokens for
        # the slot — drain defers while any are in flight
        self._unified = bool(unified)
        self._n_decode = max(0, self.decode_chunk - 1)
        self._unified_fn = None
        self._emits_inflight = np.zeros((B,), np.int32)
        # ---- speculative decoding (ISSUE 18) -------------------------
        # a drafting decode slot rides 1 + K tokens (pending + drafts)
        # through the SAME ragged mixed pass as a short prefill-shaped
        # chunk; distribution-exact rejection sampling over the target
        # logits commits the accepted prefix in place. Knobs left None
        # resolve through the autotuner cache ("spec_decode" surface,
        # registered at the bottom of this module) then static
        # defaults; an explicit argument always wins.
        self._spec = bool(spec_decode) or spec_k is not None \
            or spec_draft is not None
        if self._spec and not self._unified:
            raise ValueError("speculative decoding requires the "
                             "unified batching-step engine "
                             "(unified=True)")
        self._spec_k = 0
        self._spec_source = None
        self._spec_fn = None
        if self._spec:
            stuned = {}
            if spec_k is None or spec_draft is None:
                from ..tuner import lookup
                stuned = lookup("spec_decode",
                                {"slots": self.num_slots,
                                 "max_len": self.max_len,
                                 "page": self.page_size},
                                str(dtype)) or {}
            if spec_k is None:
                spec_k = int(stuned.get("k", 0)) or 4
            if spec_draft is None:
                spec_draft = stuned.get("source") or "ngram"
            # the verify chunk reuses the tuned [B, prefill_chunk] ids
            # plane — no new compiled shape, so K+1 must fit in it
            if self.prefill_chunk < 2:
                raise ValueError("speculative decoding needs "
                                 "prefill_chunk >= 2 to carry a "
                                 "verification chunk")
            self._spec_k = max(1, min(int(spec_k),
                                      self.prefill_chunk - 1))
            from .spec_decode import get_draft_source
            self._spec_source = get_draft_source(spec_draft)

        # perf observability (profiler subsystem): a PRIVATE typed
        # metrics registry behind the :meth:`gauges` surface — slot
        # occupancy, admission/prefill overlap, tok/s, latency
        # percentiles. Counters maintained unconditionally; latency
        # samples live in BOUNDED reservoirs (a long-lived engine's
        # memory stays flat over millions of completions — the lists
        # this replaces grew without limit); mirrored into the trace
        # layer only when tracing is enabled.
        self.metrics = _pmetrics.MetricsRegistry()
        self._stats = _StatsView(self.metrics)
        self._h_ttft = self.metrics.histogram(
            "serving/ttft_ms", capacity=int(latency_reservoir))
        self._h_itl = self.metrics.histogram(
            "serving/itl_ms", capacity=int(latency_reservoir))
        self._g_overhead = self.metrics.gauge("obs/overhead_frac")
        self._g_pc_pages = self.metrics.gauge(
            "serving/prefix_cache_pages")
        self._g_queue_depth = self.metrics.gauge("serving/queue_depth")
        self._g_kvq_bits = self.metrics.gauge("serving/kv_quant_bits")
        self._g_kvq_pool_bytes = self.metrics.gauge(
            "serving/kv_quant_pool_bytes")
        self._g_kvq_scale_bytes = self.metrics.gauge(
            "serving/kv_quant_scale_pool_bytes")
        self._c_migrated_out = self.metrics.counter(
            "disagg/migrated_out")
        self._c_kv_exported = self.metrics.counter(
            "disagg/kv_pages_exported")
        self._c_kv_imported = self.metrics.counter(
            "disagg/kv_imported_pages")
        self._c_kv_dedup = self.metrics.counter(
            "disagg/kv_import_dedup_pages")
        self._c_kv_rejects = self.metrics.counter(
            "disagg/kv_import_crc_rejects")
        self._c_spec_steps = self.metrics.counter("spec/steps")
        self._c_spec_drafted = self.metrics.counter(
            "spec/tokens_drafted")
        self._c_spec_accepted = self.metrics.counter(
            "spec/tokens_accepted")
        self._c_spec_rejected = self.metrics.counter(
            "spec/tokens_rejected")
        # observability self-measurement: seconds spent inside
        # instrumentation on the hot path (gauges()["obs_overhead_frac"]
        # = _obs_s / run_seconds; pinned < 2% by test)
        self._obs_s = 0.0
        # per-request lifecycle tracing: every Nth request (by id) gets
        # its spans reconstructed into the chrome trace at completion —
        # hot-path cost for a traced request is a few float stamps
        self._trace_every = int(round(1.0 / trace_sample_rate)) \
            if trace_sample_rate and trace_sample_rate > 0 else 0
        self._overlap_admission = False

    # ---- public API ------------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens,
                    eos_token_id=None, priority=0,
                    ttft_deadline_s=None, deadline_s=None,
                    tenant=None) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        self._check_fits(prompt.size, int(max_new_tokens))
        req = ServedRequest(self._next_id, prompt, int(max_new_tokens),
                            eos_token_id if eos_token_id is not None
                            else (self.eos if self.eos >= 0 else None),
                            priority=int(priority),
                            ttft_deadline_s=ttft_deadline_s,
                            deadline_s=deadline_s,
                            tenant=tenant)
        req.t_arrive = time.perf_counter()
        self._next_id += 1
        if req.priority:
            self._has_priorities = True
        if ttft_deadline_s is not None or deadline_s is not None:
            self._lifecycle_seen = True
        self.queue.append(req)
        return req.request_id

    def _check_fits(self, prompt_len, max_new):
        if prompt_len + max_new > self.max_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens "
                f"({max_new}) exceeds engine max_len {self.max_len}")
        # reject what the pool can NEVER satisfy — otherwise run() would
        # spin forever waiting for pages that cannot exist
        need = -(-(prompt_len + max_new) // self.page_size)
        if need > self.num_pages - 1:
            raise ValueError(
                f"request needs {need} pages but the pool only has "
                f"{self.num_pages - 1} allocatable")

    def _queue_snapshot(self):
        """Copy the queue for a cross-thread lookup. ``list(deque)``
        is NOT atomic — a scheduler mutation mid-copy raises
        mutated-during-iteration — so retry; the queue quiesces within
        a turn, making livelock practically impossible. The handle's
        own ``cancel()`` (a bool set) remains the truly lock-free
        any-thread surface."""
        while True:
            try:
                return list(self.queue)
            except RuntimeError:
                continue

    def request(self, request_id) -> ServedRequest | None:
        """The live ServedRequest handle for an id — queued, running,
        or completed (the cancel()/error/priority surface)."""
        for req in self._queue_snapshot():
            if req is not None and req.request_id == request_id:
                return req
        for req in list(self.slot_req):
            if req is not None and req.request_id == request_id:
                return req
        for req in list(self.completed):
            if req.request_id == request_id:
                return req
        return None

    def cancel(self, request_id) -> bool:
        """Cancel a queued or running request: takes effect at the next
        scheduler turn (pages freed mid-prefill or mid-decode, typed
        ``RequestCancelled`` completion, tokens already emitted kept).
        Returns False for an unknown or already-finished request.
        Only live containers are scanned — cancelling a finished
        request is a no-op, so lookup cost never grows with the
        engine's completed history."""
        for req in self._queue_snapshot() + list(self.slot_req):
            if req is not None and req.request_id == request_id:
                if req.finished:
                    return False
                req.cancel()
                self._lifecycle_seen = True
                return True
        return False

    def requeue(self, req: ServedRequest):
        """Adopt a ServedRequest salvaged from a torn-down engine
        (EngineSupervisor restart): idempotent replay — the prompt plus
        every token already delivered re-prefills through the recompute
        path, so the stream continues exactly where the dead engine
        left it. A request that already has its full stream (it crashed
        between harvest and drain) completes immediately."""
        if req.finished:
            self.completed.append(req)
            return
        self._check_fits(req.prompt.size, req.max_new_tokens)
        self._next_id = max(self._next_id, req.request_id + 1)
        if req.priority:
            self._has_priorities = True
        if req.ttft_deadline_s is not None \
                or req.deadline_s is not None or req.cancelled:
            self._lifecycle_seen = True
        self.queue.append(req)

    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.active.any()) \
            or bool(self._prefilling.any())

    def handoff(self):
        """Elasticity/drain hook (ISSUE 11): evict every unfinished
        occupant for recompute-style replay and empty the queue;
        returns the unfinished requests in arrival order — pages
        reclaimed audit-clean, tokens already emitted kept — for
        adoption by a sibling engine (the ServingFleet's deadline-
        bounded scale-down and failover paths). The engine is left
        empty and reusable."""
        out = []
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None or req.finished:
                continue
            req.preemptions += 1
            self._evict_slot(slot, requeue=False, reason="handoff")
            out.append(req)
        while self.queue:
            req = self.queue.popleft()
            if not req.finished:
                out.append(req)
        out.sort(key=lambda r: (r.t_arrive, r.request_id))
        self._audit_pages("handoff")
        return out

    # ---- disaggregated prefill/decode: KV-page migration (ISSUE 17) ------
    #
    # A ``role="prefill"`` engine never activates decode (see
    # _stage_slot): a slot streams its prompt, samples the first token
    # in-program and goes inactive, and the drain pass exports it —
    # full prompt-KV pages plus the request (first token kept) — into
    # ``migrations_out`` for the router. The destination seeds the
    # pages into ITS prefix-cache radix index and requeues the request,
    # so admission attaches them exactly like a prefix-cache hit at
    # full match length and re-prefills only the unseen suffix: greedy
    # streams are token-identical to the colocated engine by the same
    # recompute-replay contract every failover path already leans on,
    # and a lost/damaged transfer degrades to plain prompt replay, not
    # a wrong stream.

    def _should_migrate(self, slot, req):
        """True when a drained slot's request should leave this engine
        for a decode replica instead of completing here: prefill role,
        decode budget left, stream not already over (instant-eos and
        single-token requests complete locally like any engine's)."""
        if self.role != "prefill" or req.finished or req.cancelled:
            return False
        if getattr(req, "no_migrate", False):
            # the fleet found no decode-capable replica for this
            # request: complete it colocated (cross-role degradation,
            # never a migrate/replay livelock)
            return False
        if len(req.tokens) >= req.max_new_tokens:
            return False
        eos = req.eos_token_id
        if eos is not None and req.tokens and req.tokens[-1] == eos:
            return False
        return True

    def _migrate_out(self, slot, req):
        """Export a prefill-complete slot: serialize its FULL prompt-KV
        pages (per-pool crc32 per page), pin the published prefix
        against eviction until the destination acks, free the slot, and
        park (request, payload) for the router. The request does NOT
        complete here — it leaves the engine still live."""
        eff = self._slot_prompt[slot]
        ps = self.page_size
        row = self.tables[slot]
        blocks = []
        for lvl in range(len(eff) // ps):
            page = int(row[lvl])
            # np.asarray forces the device sync; a drained slot is
            # inactive in every dispatched program (its writes are
            # trash-page-guarded), so the fetched content is the final
            # prefill output even under the pipelined driver
            data = [np.asarray(p._data[:, page]) for p in self.pools]
            blocks.append({
                "tokens": np.asarray(
                    eff[lvl * ps:(lvl + 1) * ps], np.int32),
                "data": data,
                "crc": [zlib.crc32(np.ascontiguousarray(d).tobytes())
                        for d in data],
            })
        payload = {"version": 1, "rid": int(req.request_id),
                   "eff_len": int(len(eff)), "page_size": ps,
                   "n_pools": self._n_pools,
                   "dtype": str(self._pool_dtype),
                   "kv_quant": self.kv_quant,
                   "blocks": blocks}
        # deferred-free discipline (ISSUE 17): the source's published
        # prefix stays pinned until release_exported — a transfer that
        # dies mid-flight replays against warm source pages
        chain = self._pc_match(eff)
        if chain:
            self._pc_pin(chain)
            self._exported_pins[int(req.request_id)] = chain
        record_hop(req, "migrate_out",
                   replica=getattr(self, "_fleet_replica_id", None),
                   pages=len(blocks), tokens=len(req.tokens))
        _t_obs = time.perf_counter()
        self._c_migrated_out.inc()
        self._c_kv_exported.inc(len(blocks))
        _frec.record_event("migrate_out", req=req.request_id,
                           slot=slot, pages=len(blocks))
        self._obs_s += time.perf_counter() - _t_obs
        self._release_pages(self.slot_pages[slot], safe=True)
        self._clear_slot(slot)
        self.migrations_out.append((req, payload))

    def take_migrations(self):
        """Drain the outbound migration queue: (request, payload)
        pairs in export order, for the router (or the worker RPC seam)
        to deliver to a decode replica."""
        out = []
        while self.migrations_out:
            out.append(self.migrations_out.popleft())
        return out

    def release_exported(self, request_id):
        """Destination ack: unpin a migrated request's exported prefix
        pages on the SOURCE engine (they stay resident as ordinary
        evictable cache — that residency is the warm-prefix win for
        repeated prompts). Idempotent; returns whether a pin existed."""
        chain = self._exported_pins.pop(int(request_id), None)
        if chain is None:
            return False
        self._pc_unpin(chain)
        self._audit_pages("release_exported")
        return True

    def import_migration(self, req, payload):
        """Adopt a migrated request WITH its shipped KV: verify each
        block's checksums, write accepted pages into the pools (one
        compiled functional dispatch for the whole request — chains
        behind any in-flight program, the COW discipline), seed them
        into the
        prefix-cache radix index as evictable residents, then requeue
        the request. Admission then attaches the seeded chain like any
        prefix-cache hit. Idempotent: blocks already resident dedup;
        ANY malformed/damaged block stops seeding (the chain must stay
        root-contiguous) and the request still replays correctly from
        whatever prefix landed. Returns import counts."""
        imported = dedup = rejected = 0
        pending = []          # (page, [per-pool np page content])
        ok = (self._prefix_cache and isinstance(payload, dict)
              and payload.get("version") == 1
              and payload.get("page_size") == self.page_size
              and payload.get("n_pools") == self._n_pools
              and payload.get("dtype") == str(self._pool_dtype)
              # geometry handshake: quantized pages only land in a
              # same-kv_quant pool (a mixed pair falls back to the
              # tokens-only recompute path — the requeue below)
              and payload.get("kv_quant", "none") == self.kv_quant)
        if ok:
            self._pc_clock += 1
            cur = self._pc_root
            for blk in payload.get("blocks") or []:
                toks = np.asarray(blk["tokens"],
                                  np.int32).reshape(-1)
                if toks.size != self.page_size:
                    rejected += 1
                    break
                key = toks.tobytes()
                child = cur.children.get(key)
                if child is not None:
                    child.stamp = self._pc_clock
                    cur = child
                    dedup += 1
                    continue
                data = blk.get("data") or []
                crcs = blk.get("crc")
                if len(data) != self._n_pools or (
                        crcs is not None
                        and [zlib.crc32(np.ascontiguousarray(
                                d).tobytes()) for d in data]
                        != [int(c) for c in crcs]):
                    rejected += 1
                    break
                alloc = self._alloc_pages(1)
                if alloc is None:
                    break        # pool pressure: partial seed is fine
                page = alloc[0]
                pending.append(
                    (page, [np.ascontiguousarray(d) for d in data]))
                node = _PrefixCacheNode(key, page, cur)
                node.stamp = self._pc_clock
                cur.children[key] = node
                self._pc_nodes[page] = node
                cur = node
                imported += 1
        if pending:
            # defer the device write until every block has been
            # verified/alloc'd, then land the whole request in one
            # batched dispatch (nothing dispatches between alloc and
            # here — the engine is single-threaded, so a node briefly
            # pointing at an unwritten page is unobservable). Pad to
            # the per-request page bound with copies of the last page
            # so every import shares ONE compiled shape — duplicate
            # scatter indices carrying identical content are
            # order-independent, and per-count shapes would recompile
            # mid-pump, putting XLA compiles on the migration path.
            width = max(len(pending), self.pages_per_slot)
            padded = pending + [pending[-1]] * (width - len(pending))
            dst = jnp.asarray([p for p, _ in padded], jnp.int32)
            stacked = [jnp.asarray(
                np.stack([d[i] for _, d in padded], axis=1),
                self._pool_dtypes[i]) for i in range(self._n_pools)]
            self.pools = [Tensor(a) for a in _kv_write_pages(
                [p._data for p in self.pools], dst, stacked)]
        _t_obs = time.perf_counter()
        if imported:
            self._c_kv_imported.inc(imported)
        if dedup:
            self._c_kv_dedup.inc(dedup)
        if rejected:
            self._c_kv_rejects.inc(rejected)
        _frec.record_event("migrate_in", req=req.request_id,
                           imported=imported, dedup=dedup,
                           rejected=rejected)
        self._obs_s += time.perf_counter() - _t_obs
        record_hop(req, "migrate_in",
                   replica=getattr(self, "_fleet_replica_id", None),
                   imported=imported, dedup=dedup,
                   rejected=rejected)
        self.requeue(req)
        self._audit_pages("kv_import")
        return {"imported": imported, "dedup": dedup,
                "rejected": rejected}

    def step(self):
        """Admit what fits, advance every slot one scheduler turn (one
        unified batching-step program, or prefill waves + one decode
        chunk in legacy mode), drain finished slots. Returns the
        requests completed by this step. Step failures hit the same
        containment boundary as :meth:`run`."""
        self._admit()
        try:
            if self._unified:
                if self._worth_step():
                    # spec engines speculate in step()-pumped drivers
                    # too (ApiServer, fleet replicas), not just run()
                    self._harvest_step(self._dispatch_spec_step()
                                       if self._spec else
                                       self._dispatch_step())
            else:
                self._pump_prefill()
                if self.active.any():
                    self._decode_chunk()
        except Exception as exc:  # noqa: BLE001 — containment boundary
            if not self._containable(exc):
                raise
            return self._contain_step_failure(exc) + self._drain()
        return self._drain()

    def run(self):
        """Drive until every queued request completes; returns them in
        completion order.

        Pipelined: the NEXT chunk is ALWAYS dispatched before the
        previous chunk's packed output is fetched — device state chains
        asynchronously, so the harvest round-trip AND the whole
        admission wave (prefill-chunk programs, slot-state updates)
        execute while the speculative successor decodes on device: a
        prefill wave consumes the successor's output pools, so it simply
        joins the device stream after it, and an admitted slot starts
        decoding in the chunk after its final prefill wave. A slot that
        finished inside the previous chunk is inactive in the
        speculative successor (its device active flag is already False),
        so the overlap never decodes garbage. The successor is SKIPPED
        when the host can prove it would do no work (every active slot's
        predicted remaining budget is zero) — with adaptive chunk
        lengths that proof fires exactly at each drain wave, so the
        round-4 "one wasted chunk program per drain wave" cost is gone
        (``chunks_empty`` measures any residue, e.g. eos stops the host
        cannot predict).

        Unified mode runs the SAME driver with its own hooks: the
        speculative successor is a whole batching-step program, there
        is no separate prefill pump (prompt streaming, activation, the
        first-token sample and the decode tail all live inside the
        step), and the successor is skipped when no prefilling slot
        exists and every active slot's predicted budget is exhausted."""
        if self._unified:
            if self._spec:
                # speculative decoding runs the SAME driver SERIALLY:
                # drafts are functions of the harvested token history
                # (n-gram lookup) or of the post-harvest device state
                # (self-spec), so a speculative successor dispatched
                # before harvest would draft from a stale stream. The
                # round trip it un-hides is amortized by the ~K tokens
                # each step emits instead of one.
                return self._run_driver(
                    spec_dispatch=lambda: None,
                    harvest=self._harvest_step,
                    after_admit=lambda: None,
                    idle_turn=self._idle_turn_spec)
            return self._run_driver(
                spec_dispatch=lambda: self._dispatch_step()
                if self._worth_step() else None,
                harvest=self._harvest_step,
                after_admit=lambda: None,
                idle_turn=self._idle_turn_unified)
        return self._run_driver(
            spec_dispatch=lambda: self._dispatch_chunk()
            if self._worth_dispatching() else None,
            harvest=self._harvest_chunk,
            # ONE prefill wave per scheduler turn: prompt streaming
            # interleaves with decode chunks instead of stalling them
            after_admit=lambda: self._pump_prefill(max_waves=1),
            idle_turn=self._idle_turn_legacy)

    def _idle_turn_unified(self):
        """Nothing in flight: dispatch a step if it would advance
        anything. Returns (progressed, inflight record or None)."""
        if self._worth_step():
            return True, self._dispatch_step()
        return False, None

    def _idle_turn_spec(self):
        """Serial speculative turn: draft + dispatch one spec step if
        it would advance anything."""
        if self._worth_step():
            return True, self._dispatch_spec_step()
        return False, None

    def _idle_turn_legacy(self):
        """Nothing in flight: stream one prefill wave if prompts are
        pending, else dispatch a decode chunk if slots are active."""
        if self._prefilling.any():
            self._pump_prefill(max_waves=1)
            return True, None
        if self.active.any():
            return True, self._dispatch_chunk()
        return False, None

    def _run_driver(self, spec_dispatch, harvest, after_admit,
                    idle_turn):
        """The one scheduler loop both modes share — hooks differ, the
        pipelining skeleton, overlap-admission accounting, the fault-
        containment boundary and stall detection must not (a fix here
        fixes both engines).

        Reliability structure (ISSUE 10): every compiled-step
        dispatch/harvest runs inside the containment boundary — a step
        exception quarantines the implicated request(s) and resets
        slots/pages instead of killing the engine. Pure overload never
        stalls: a no-progress turn with occupied slots evicts the
        youngest, lowest-priority occupant for recompute (a wedged slot
        cannot hold the pool hostage); the stall ``RuntimeError``
        survives only as the watchdog-backed deadlock diagnostic for a
        pool that is exhausted with NO occupant left to evict (a true
        leak)."""
        done = []
        inflight = None
        deadlock_evictions = 0
        max_deadlock = max(8, 2 * self.num_slots)
        # the containment budget is PER RUN: a healthy later run must
        # not inherit an earlier run's spent budget
        self._containments_run = 0

        def contained(exc, cohort=None):
            """Quarantine/requeue for a containable compiled-step
            failure; None when the failure must escape (audit
            assertion, budget spent — the EngineSupervisor's job).
            ``cohort``: the failed program's dispatch-time request
            snapshot, for accurate blame."""
            if not self._containable(exc):
                return None
            return self._contain_step_failure(exc, cohort=cohort)

        t_run0 = time.perf_counter()
        _wd_token = _frec.arm("serving run loop")
        try:
            while True:
                # watchdog progress mark: a hung device fetch or a
                # scheduler livelock stops the beats and the flight
                # recorder dumps a diagnosable bundle (owner-token
                # scoped: another component's beats cannot mask us)
                _frec.beat(_wd_token)
                if inflight is not None:
                    # speculative successor first: device never
                    # idles while the host harvests/drains/admits.
                    # Containment wraps ONLY the compiled dispatch/
                    # harvest — a host-side scheduler bug in
                    # _admit/_drain/_reap is not a per-request fault
                    # and must surface, not be laundered into strikes
                    try:
                        nxt = spec_dispatch()
                    except Exception as exc:  # noqa: BLE001
                        extra = contained(exc)
                        if extra is None:
                            raise
                        inflight = None
                        done.extend(extra)
                        continue
                    try:
                        harvest(inflight)
                    except Exception as exc:  # noqa: BLE001
                        # blame the HARVESTED program's dispatch-time
                        # cohort (rec[1]), not whoever occupies the
                        # slots now
                        extra = contained(exc, cohort=inflight[1])
                        if extra is None:
                            raise
                        inflight = None
                        done.extend(extra)
                        continue
                    done.extend(self._drain())
                    # admissions overlap nxt's on-device run — the
                    # gauge distinguishing overlapped / serialized
                    self._overlap_admission = nxt is not None
                    try:
                        self._admit()
                        try:
                            # legacy prefill waves ARE compiled
                            # dispatches — containable; nxt is
                            # abandoned with the rest of device state
                            after_admit()
                        except Exception as exc:  # noqa: BLE001
                            extra = contained(exc)
                            if extra is None:
                                raise
                            nxt = None
                            done.extend(extra)
                    finally:
                        self._overlap_admission = False
                    inflight = nxt
                    continue
                n_before = len(done)
                self._admit()
                done.extend(self._drain())
                try:
                    progressed, inflight = idle_turn()
                except Exception as exc:  # noqa: BLE001
                    extra = contained(exc)
                    if extra is None:
                        raise
                    inflight = None
                    done.extend(extra)
                    continue
                if progressed or len(done) > n_before:
                    # a recovered wedge must not eat the deadlock
                    # budget forever: the cap bounds CONSECUTIVE
                    # fruitless evictions, not a run's lifetime total
                    deadlock_evictions = 0
                    continue
                if not self.queue:
                    break
                # nothing dispatched, harvested, drained or admitted
                # this turn, but requests still queued: overload always
                # progresses (slots drain -> pages free -> admission),
                # so something undrainable holds the pool
                occupied = [s for s in range(self.num_slots)
                            if self.slot_req[s] is not None]
                if occupied and deadlock_evictions < max_deadlock:
                    victim = min(occupied, key=lambda s: (
                        self.slot_req[s].priority,
                        -self.slot_req[s].t_admit))
                    deadlock_evictions += 1
                    self._evict_slot(victim, requeue=True,
                                     reason="deadlock")
                    continue
                # pool exhausted with no evictable occupant (or the
                # eviction budget burned without progress): a true
                # leak/deadlock. Dump a flight-recorder bundle first:
                # the ring's recent scheduler turns + pool state are
                # the post-mortem
                rec = _frec.get_recorder()
                if rec is not None:
                    _frec.record_event(
                        "serving_stall", queued=len(self.queue),
                        free_pages=len(self._free_pages),
                        occupied=len(occupied))
                    try:
                        rec.dump("serving engine stalled: queued "
                                 "request cannot be admitted")
                    except OSError:
                        pass    # the diagnostic RuntimeError below
                                # must not be replaced by a failed
                                # bundle write
                raise RuntimeError(
                    "serving engine stalled: queued request cannot "
                    "be admitted (page pool exhausted?)")
        finally:
            _frec.disarm(_wd_token)
            self._stats["run_seconds"] += time.perf_counter() - t_run0
            self._emit_gauges()
        return done

    # ---- step-level fault containment (ISSUE 10) -------------------------

    def _containable(self, exc):
        """Is this step failure containable? AssertionError is the
        audit invariant speaking — never swallow it; past the per-run
        containment budget the failure escapes to the
        EngineSupervisor (an engine failing every step must not loop
        forever)."""
        if isinstance(exc, AssertionError):
            return False
        return self._containments_run < self.max_containments

    def _contain_step_failure(self, exc, cohort=None):
        """Step-level fault isolation: one failed compiled step (a
        poisoned sampler, NaN materializing at the fetch, an injected
        fault) must not kill every in-flight stream. Every occupied
        slot gets a STRIKE — a poison request rides every step it is
        scheduled into, so repeat offenders cross ``max_strikes`` and
        are quarantined with a typed error, while co-scheduled
        innocents are requeued for recompute-style replay (suspects
        re-enter SOLO, so the next fault implicates exactly one
        request). Device state after a failed step is unreliable (the
        pools/hot-state chain ran through the failed program), so it
        is rebuilt from scratch and every survivor replays through the
        recompute path. Returns the requests completed (quarantined)
        by the containment.

        ``cohort`` is the failed program's DISPATCH-TIME request
        snapshot when the caller has one (a harvest record): only
        cohort members are struck — a request admitted during the
        overlap window must not be blamed for a program it never
        rode (it still resets and replays, unblamed)."""
        self._containments_run += 1
        self._stats.inc("containments")
        _frec.record_event(
            "containment", error=repr(exc)[:200],
            occupied=int(sum(r is not None for r in self.slot_req)))
        blame = None if cohort is None else \
            {id(r) for r in cohort if r is not None}
        requeue, quarantine = [], []
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None or req.finished:
                continue
            if blame is None or id(req) in blame:
                req.strikes += 1
            (quarantine if req.strikes >= self.max_strikes
             else requeue).append(req)
        self._reset_device_state()
        done = []
        for req in requeue:
            req.preemptions += 1
        # survivors replay in ARRIVAL order at the queue front
        # (appendleft in slot order would reverse it — later arrivals
        # must not replay first; slot order itself is shuffled by
        # drain/re-admit churn)
        requeue.sort(key=lambda r: (r.t_arrive, r.request_id))
        self.queue.extendleft(reversed(requeue))
        for req in quarantine:
            done.append(self._finish_error(
                req, RequestQuarantined(req.request_id, repr(exc))))
        self._audit_pages("containment")
        return done

    def _reset_device_state(self):
        """Rebuild the pools, the free list and all per-slot state from
        scratch — FRESH device buffers, so writes still racing out of
        an abandoned in-flight program land in orphaned arrays, never
        in state the engine will read again. Compiled programs are pure
        functions of their inputs and are kept."""
        B, MP = self.num_slots, self.pages_per_slot
        self.pools = [Tensor(jnp.zeros(s, dt)) for s, dt in
                      zip(self._pool_shapes, self._pool_dtypes)]
        self._free_pages = deque(range(1, self.num_pages))
        self._deferred_free = []
        self.tables[:] = 0
        self.ctx[:] = 0
        self.active[:] = False
        self.limits[:] = 0
        self.slot_eos[:] = -1
        self.slot_req = [None] * B
        self.slot_pages = [[] for _ in range(B)]
        # the rebuilt pools are zeroed, so every cached page's content
        # is gone with them: drop the whole radix index (its pages are
        # already back in the rebuilt free list)
        self.slot_shared = [[] for _ in range(B)]
        self._pc_root = _PrefixCacheNode(None, 0, None)
        self._pc_nodes = {}
        # exported-prefix pins die with the index they pointed into;
        # the parked migration payloads are host-side copies and
        # survive (the router still delivers them)
        self._exported_pins = {}
        self._slot_prompt = [None] * B
        self._prefilling[:] = False
        self._prefill_off[:] = 0
        self._act_target[:] = False
        self._pred_ctx[:] = 0
        self._act_since[:] = 0
        self._pending_first[:] = False
        self._echo_inflight[:] = False
        self._emits_inflight[:] = 0
        self._dev_tok = jnp.zeros((B,), jnp.int32)
        self._dev_ctx = jnp.zeros((B,), jnp.int32)
        self._dev_act = jnp.zeros((B,), bool)
        self._dev_tbl = jnp.zeros((B, MP), jnp.int32)
        self._dev_lim = jnp.zeros((B,), jnp.int32)
        self._dev_eos = jnp.full((B,), -1, jnp.int32)
        # the RNG key chained through the failed program; rebuild from
        # the seed (greedy streams are unaffected; sampled streams
        # restart their key chain — documented in docs/serving.md)
        self._key = jax.random.PRNGKey(
            self._seed + self._containments_run)
        self._last_fetch_dispatch_seq = self._seq
        self._last_harvest_seq = self._seq

    # ---- unified batching step (ONE compiled program) --------------------

    def _worth_step(self):
        """Would a unified step advance anything? Prefilling slots
        always do; decode slots only while the host's ctx prediction
        leaves budget (an eos stop the host cannot see may still yield
        an empty step — counted in ``chunks_empty``)."""
        return bool(self._prefilling.any()
                    or np.any(self.active
                              & (self.limits > self._pred_ctx)))

    def _unified_static(self):
        """The ONE compiled batching-step program: a ragged mixed pass
        (prefill slots stream their next ``prefill_chunk`` prompt
        tokens, active decode slots ride their pending token as a
        length-1 sequence, idle slots are length 0 — one
        [num_slots, prefill_chunk] forward through
        ``ragged_paged_attention``) followed by ``decode_chunk - 1``
        in-program decode micro-steps. A slot whose prompt completes in
        the mixed pass samples its first token and starts decoding at
        micro-step 1 — prefill→decode transition never leaves the
        device, so no first-token echo machinery exists in this mode.
        The packed output carries every emitted token of the step plus
        the ctx/active mirrors in ONE int32 fetch."""
        if self._unified_fn is not None:
            return self._unified_fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature
        C = self.prefill_chunk
        n_dec = self._n_decode

        def ustep(ids_t, nq_t, last_t, tgt_t, tok_t, ctx_t, act_t,
                  tbl_t, lim_t, eos_t, key_t, *pools):
            fwd = model.forward

            def fn(ids, nq, last, tgt, tok, ctx, act, tbl, lim,
                   eos_arr, key, *pool_leaves):
                b = tok.shape[0]
                # stale instant-eos guard (legacy chunk-entry contract)
                act = act & ((eos_arr < 0) | (tok != eos_arr))
                is_pre = nq > 0
                lengths = jnp.where(
                    is_pre, nq,
                    jnp.where(act, 1, 0)).astype(jnp.int32)
                # decode slots carry their device-resident pending
                # token in stream column 0
                ids_eff = ids.at[:, 0].set(
                    jnp.where(is_pre, ids[:, 0], tok))
                with no_grad():
                    logits, npools = fwd(
                        Tensor(ids_eff),
                        caches=[Tensor(a) for a in pool_leaves],
                        pos=Tensor(ctx[:, None]),
                        tables=(Tensor(tbl), Tensor(lengths)))
                lg = logits._data                      # [B, C, V]
                idx = jnp.clip(lengths - 1, 0, C - 1)
                last_lg = jnp.take_along_axis(
                    lg, idx[:, None, None], axis=1)[:, 0]
                last_lg = last_lg.astype(jnp.float32)
                if greedy:
                    sampled = jnp.argmax(last_lg, -1).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    sampled = jax.random.categorical(
                        sub, last_lg / temperature).astype(jnp.int32)
                # a next-token fires for completing prompts and for
                # advancing decode slots
                fire = (is_pre & last) | (act & ~is_pre)
                nxt = jnp.where(fire, sampled, tok)
                ctx1 = ctx + lengths
                hit_eos = (eos_arr >= 0) & (nxt == eos_arr)
                still_dec = act & ~is_pre & (ctx1 < lim) & ~hit_eos
                act_pre = is_pre & last & tgt & (ctx1 < lim) & ~hit_eos
                act1 = jnp.where(is_pre, act_pre, still_dec)
                out0 = jnp.where(fire, nxt, -1)

                def body(carry, _):
                    tok_c, ctx_c, act_c, key_c, leaves = carry
                    with no_grad():
                        lgs, ncaches = fwd(
                            Tensor(tok_c.reshape(b, 1)),
                            caches=[Tensor(a) for a in leaves],
                            pos=Tensor(ctx_c[:, None]),
                            tables=(Tensor(tbl), Tensor(act_c)))
                    lg_c = lgs[:, -1]._data.astype(jnp.float32)
                    if greedy:
                        nx = jnp.argmax(lg_c, -1).astype(jnp.int32)
                    else:
                        key_c, sub_c = jax.random.split(key_c)
                        nx = jax.random.categorical(
                            sub_c, lg_c / temperature).astype(jnp.int32)
                    ctx_n = ctx_c + act_c.astype(jnp.int32)
                    nx = jnp.where(act_c, nx, tok_c)
                    still = act_c & (ctx_n < lim) & \
                        ((eos_arr < 0) | (nx != eos_arr))
                    new_leaves = tuple(t._data for t in ncaches)
                    out_tok = jnp.where(act_c, nx, -1)
                    return (nx, ctx_n, still, key_c, new_leaves), \
                        (out_tok, act_c)

                carry0 = (nxt, ctx1, act1, key,
                          tuple(t._data for t in npools))
                if n_dec:
                    carry, (toks, emitted) = jax.lax.scan(
                        body, carry0, jnp.arange(n_dec))
                    tok_f, ctx_f, act_f, key_f, leaves_f = carry
                    toks_all = jnp.concatenate(
                        [out0[:, None], toks.T], axis=1)
                    emit_all = jnp.concatenate(
                        [fire[:, None], emitted.T], axis=1)
                else:
                    tok_f, ctx_f, act_f, key_f, leaves_f = carry0
                    toks_all = out0[:, None]
                    emit_all = fire[:, None]
                packed_out = jnp.concatenate(
                    [toks_all.astype(jnp.int32),
                     emit_all.astype(jnp.int32),
                     ctx_f[:, None].astype(jnp.int32),
                     act_f[:, None].astype(jnp.int32)], axis=1)
                return (packed_out, tok_f, ctx_f, act_f, key_f) \
                    + tuple(leaves_f)

            return _apply_multi(
                fn, [ids_t, nq_t, last_t, tgt_t, tok_t, ctx_t, act_t,
                     tbl_t, lim_t, eos_t, key_t] + list(pools),
                n_out=5 + len(pools))

        self._unified_fn = to_static(ustep)
        self._compiled.add(("unified", C, 1 + n_dec))
        return self._unified_fn

    def _dispatch_step(self):
        """Launch one unified step (async) and chain the device state.
        Returns an in-flight record for :meth:`_harvest_step` — the
        packed output is NOT fetched here, so a caller may overlap the
        fetch with the next step's on-device compute."""
        B, C = self.num_slots, self.prefill_chunk
        ids = np.zeros((B, C), np.int32)
        nq = np.zeros((B,), np.int32)
        last = np.zeros((B,), bool)
        tgt = np.zeros((B,), bool)
        n_pre = 0
        for slot in range(B):
            if not self._prefilling[slot] or n_pre >= self.admit_batch:
                continue
            prm = self._slot_prompt[slot]
            off = int(self._prefill_off[slot])
            v = min(C, len(prm) - off)
            ids[slot, :v] = prm[off:off + v]
            nq[slot] = v
            last[slot] = off + v == len(prm)
            tgt[slot] = self._act_target[slot]
            n_pre += 1
        fn = self._unified_static()
        self._seq += 1
        self._last_fetch_dispatch_seq = self._seq
        n_steps = 1 + self._n_decode
        # a slot advances this step if it decodes with budget left OR
        # streams prompt tokens (a completing prompt decodes the
        # in-program tail too, so its tokens must be credited here)
        n_active = int(np.sum((self.active
                               & (self.limits > self._pred_ctx))
                              | (nq > 0)))
        _t_obs = time.perf_counter()
        self._stats.inc("chunks")
        self._stats.inc("unified_steps")
        self._stats.inc("chunk_slot_steps", B * n_steps)
        if n_pre:
            self._stats.inc("prefill_waves")
        self._stats.inc("active_slot_steps", n_active * n_steps)
        from ..profiler.trace import get_tracer
        _tr = get_tracer()
        if _tr.enabled:
            _tr.counter("serving/active_slots", n_active,
                        queued=len(self.queue), chunk_len=n_steps,
                        prefilling=n_pre)
        _frec.record_event("sched_turn", seq=self._seq, mode="unified",
                           active=n_active, queued=len(self.queue),
                           prefilling=n_pre, chunk_len=n_steps)
        self._obs_s += time.perf_counter() - _t_obs
        res = fn(Tensor(jnp.asarray(ids)), Tensor(jnp.asarray(nq)),
                 Tensor(jnp.asarray(last)), Tensor(jnp.asarray(tgt)),
                 Tensor(self._dev_tok), Tensor(self._dev_ctx),
                 Tensor(self._dev_act), Tensor(self._dev_tbl),
                 Tensor(self._dev_lim), Tensor(self._dev_eos),
                 Tensor(self._key), *self.pools)
        packed, tok_f, ctx_f, act_f, key_f = res[:5]
        self.pools = list(res[5:])
        self._dev_tok = tok_f._data
        self._dev_ctx = ctx_f._data
        self._dev_act = act_f._data
        self._key = key_f._data
        # host bookkeeping: prompt-stream progress is exact; decode
        # activity is a prediction refined by the harvested mirrors
        emits = np.zeros((B,), bool)
        for slot in range(B):
            if nq[slot] > 0:
                self._prefill_off[slot] += nq[slot]
                if last[slot]:
                    req = self.slot_req[slot]
                    tl = len(self._slot_prompt[slot])
                    req.t_prefill_done = time.perf_counter()
                    self._prefilling[slot] = False
                    self.ctx[slot] = tl
                    # the first token + in-program decode tail land in
                    # THIS step; mirrors from any EARLIER in-flight
                    # step must not clobber the activation
                    self.active[slot] = bool(tgt[slot])
                    self._act_since[slot] = self._seq
                    self._pred_ctx[slot] = min(
                        int(self.limits[slot]), tl + self._n_decode)
                    # the prompt's full pages are final now (decode
                    # writes land past tl): publish them for sharing
                    self._pc_insert(slot)
                    emits[slot] = True
            elif self.active[slot] \
                    and self.limits[slot] > self._pred_ctx[slot]:
                self._pred_ctx[slot] = min(
                    int(self.limits[slot]),
                    int(self._pred_ctx[slot]) + n_steps)
                emits[slot] = True
        self._emits_inflight += emits.astype(np.int32)
        return (packed, list(self.slot_req), emits, n_steps, self._seq)

    def _harvest_step(self, rec):
        """Fetch one in-flight unified step's packed output and apply
        it: append emitted tokens, refresh the ctx/active mirrors
        (unless the slot was re-admitted, or activated by a LATER
        dispatch, since this step went out)."""
        packed, snap_req, emits, n_steps, seq = rec
        arr = np.asarray(packed._data)            # the ONE fetch
        self._last_harvest_seq = max(self._last_harvest_seq, seq)
        self._release_deferred()
        toks_np = arr[:, :n_steps]
        emitted_np = arr[:, n_steps:2 * n_steps].astype(bool)
        ctx_m = arr[:, 2 * n_steps].astype(np.int32)
        act_m = arr[:, 2 * n_steps + 1].astype(bool)
        t_now = time.perf_counter()
        appended = 0
        for slot in range(self.num_slots):
            req = snap_req[slot]
            if req is not self.slot_req[slot]:
                continue      # slot re-admitted since this dispatch
            if emits[slot]:
                self._emits_inflight[slot] -= 1
            if self._act_since[slot] <= seq:
                self.ctx[slot] = ctx_m[slot]
                self.active[slot] = act_m[slot]
                self._pred_ctx[slot] = max(int(self._pred_ctx[slot]),
                                           int(ctx_m[slot]))
            if req is None or req.finished:
                continue
            # a clean harvest exonerates its riders: one solo step
            # clears a suspect, so a containment cannot serialize the
            # whole batch into solo-to-completion replays
            req.strikes = 0
            for j in range(n_steps):
                if emitted_np[slot, j]:
                    if not req.tokens:
                        req.t_first = t_now
                    req.tokens.append(int(toks_np[slot, j]))
                    appended += 1
        _t_obs = time.perf_counter()
        self._stats.inc("tokens_emitted", appended)
        if appended == 0:
            self._stats.inc("chunks_empty")
        # a SPEC step's packed output carries two extra accounting
        # columns (committed-draft and drafted counts per slot) past
        # the layout this method parses — fold them into the spec
        # economics counters
        if arr.shape[1] > 2 * n_steps + 2:
            nds = arr[:, 2 * n_steps + 3]
            accs = arr[:, 2 * n_steps + 2]
            drafted = int(nds.sum())
            if drafted:
                committed = int(accs.sum())
                self._c_spec_drafted.inc(drafted)
                self._c_spec_accepted.inc(committed)
                self._c_spec_rejected.inc(drafted - committed)
        self._obs_s += time.perf_counter() - _t_obs

    # ---- speculative decoding (ISSUE 18) ---------------------------------

    def _unified_spec_static(self):
        """The speculative batching-step program: the SAME ragged mixed
        pass as :meth:`_unified_static` — prefill slots stream prompt
        chunks unchanged — but an active decode slot rides ``1 + n_d``
        tokens (its pending token in column 0, host-proposed draft
        tokens in columns ``1..n_d``) as a short prefill-shaped chunk,
        and the ``decode_chunk - 1`` scan tail is replaced by
        DISTRIBUTION-EXACT verification of the drafts against the
        target logits:

        - greedy: accept while the draft matches the argmax (so spec
          streams are token-identical to the plain engine);
        - sampling: accept draft ``d_j`` with prob ``min(1, p_j[d_j])``
          (point-mass draft), resample the first rejection from the
          renormalized residual, bonus-sample from ``p_K`` when every
          draft holds — each emitted position marginally exact.

        Accepted tokens COMMIT by advancing ctx over their already-
        written KV (``ops.paged_attention.paged_verify_write``
        semantics); rejected positions simply stay behind ctx, unread
        and overwritten by the next chunk. The packed output keeps the
        harvest layout with ``n_steps = K + 1`` plus two trailing
        accounting columns (committed drafts, drafted count)."""
        if self._spec_fn is not None:
            return self._spec_fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature
        C = self.prefill_chunk
        K = self._spec_k

        def sstep(ids_t, nq_t, last_t, tgt_t, nd_t, tok_t, ctx_t,
                  act_t, tbl_t, lim_t, eos_t, key_t, *pools):
            fwd = model.forward

            def fn(ids, nq, last, tgt, nd, tok, ctx, act, tbl, lim,
                   eos_arr, key, *pool_leaves):
                b = tok.shape[0]
                # stale instant-eos guard (same as the plain step)
                act = act & ((eos_arr < 0) | (tok != eos_arr))
                is_pre = nq > 0
                dec = act & ~is_pre
                # drafts were clamped host-side against the host ctx;
                # re-gate on the device view (the eos guard above can
                # retire a slot the host still believed active)
                nd_eff = jnp.where(dec, nd, 0).astype(jnp.int32)
                lengths = jnp.where(
                    is_pre, nq,
                    jnp.where(dec, 1 + nd_eff, 0)).astype(jnp.int32)
                ids_eff = ids.at[:, 0].set(
                    jnp.where(is_pre, ids[:, 0], tok))
                with no_grad():
                    logits, npools = fwd(
                        Tensor(ids_eff),
                        caches=[Tensor(a) for a in pool_leaves],
                        pos=Tensor(ctx[:, None]),
                        tables=(Tensor(tbl), Tensor(lengths)))
                lg = logits._data                      # [B, C, V]
                # ---- decode slots: verify drafts on columns 0..K ----
                vlg = lg[:, :K + 1].astype(jnp.float32)
                d = ids[:, 1:K + 1].astype(jnp.int32)  # [B, K]
                jk = jnp.arange(K)[None, :]
                if greedy:
                    tgt_tok = jnp.argmax(vlg, -1).astype(jnp.int32)
                    acc = d == tgt_tok[:, :K]
                else:
                    p = jax.nn.softmax(vlg / temperature, axis=-1)
                    key, sub_u = jax.random.split(key)
                    u = jax.random.uniform(sub_u, (b, K))
                    pd = jnp.take_along_axis(
                        p[:, :K], d[:, :, None], axis=2)[:, :, 0]
                    acc = u < pd
                acc = acc & (jk < nd_eff[:, None])
                # leading-run length = accepted draft count
                n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32),
                                            axis=1), axis=1)
                # target token at the first unaccepted position:
                # rejection resample (draft zeroed, renormalized) or
                # the bonus sample when every draft held
                if greedy:
                    fin = jnp.take_along_axis(
                        tgt_tok, n_acc[:, None], axis=1)[:, 0]
                else:
                    row = jnp.take_along_axis(
                        p, n_acc[:, None, None], axis=1)[:, 0]
                    d_at = jnp.take_along_axis(
                        d, jnp.clip(n_acc, 0, K - 1)[:, None],
                        axis=1)[:, 0]
                    rej = n_acc < nd_eff
                    v_ax = jnp.arange(row.shape[-1])[None, :]
                    row = jnp.where(
                        rej[:, None] & (v_ax == d_at[:, None]),
                        0.0, row)
                    key, sub_f = jax.random.split(key)
                    fin_lg = jnp.where(row > 0, jnp.log(row), -1e30)
                    fin = jax.random.categorical(
                        sub_f, fin_lg).astype(jnp.int32)
                # emission ladder e_0..e_K: accepted drafts, then the
                # target sample; trimmed by per-position ctx budget
                # and a mid-chunk eos (the eos token itself emits,
                # nothing after it — the plain-engine contract)
                d_pad = jnp.concatenate(
                    [d, jnp.zeros((b, 1), jnp.int32)], axis=1)
                jk1 = jnp.arange(K + 1)[None, :]
                e = jnp.where(jk1 < n_acc[:, None], d_pad,
                              fin[:, None])
                eos_hit = (eos_arr[:, None] >= 0) & \
                    (e == eos_arr[:, None])
                eos_before = jnp.cumsum(
                    eos_hit.astype(jnp.int32), axis=1) - \
                    eos_hit.astype(jnp.int32)
                alive = (jk1 <= n_acc[:, None]) \
                    & ((ctx[:, None] + jk1) < lim[:, None]) \
                    & (eos_before == 0) & dec[:, None]
                n_emit = jnp.sum(alive.astype(jnp.int32), axis=1)
                ctx_dec = ctx + n_emit
                last_e = jnp.take_along_axis(
                    e, jnp.clip(n_emit - 1, 0, K)[:, None],
                    axis=1)[:, 0]
                tok_dec = jnp.where(n_emit > 0, last_e, tok)
                still_dec = dec & (n_emit > 0) & (ctx_dec < lim) \
                    & ((eos_arr < 0) | (last_e != eos_arr))
                # ---- prefill slots: plain-step single sample --------
                idx = jnp.clip(lengths - 1, 0, C - 1)
                last_lg = jnp.take_along_axis(
                    lg, idx[:, None, None],
                    axis=1)[:, 0].astype(jnp.float32)
                if greedy:
                    sampled = jnp.argmax(last_lg, -1).astype(jnp.int32)
                else:
                    key, sub_p = jax.random.split(key)
                    sampled = jax.random.categorical(
                        sub_p, last_lg / temperature).astype(jnp.int32)
                fire_pre = is_pre & last
                ctx1 = ctx + lengths
                hit_eos_pre = (eos_arr >= 0) & (sampled == eos_arr)
                act_pre = fire_pre & tgt & (ctx1 < lim) & ~hit_eos_pre
                # ---- merge + pack -----------------------------------
                toks_all = jnp.where(dec[:, None], e, -1)
                toks_all = toks_all.at[:, 0].set(
                    jnp.where(fire_pre, sampled, toks_all[:, 0]))
                emit_all = alive.at[:, 0].set(
                    fire_pre | alive[:, 0])
                tok_f = jnp.where(dec, tok_dec,
                                  jnp.where(fire_pre, sampled, tok))
                ctx_f = jnp.where(dec, ctx_dec, ctx + lengths)
                act_f = jnp.where(is_pre, act_pre,
                                  jnp.where(dec, still_dec, act))
                committed = jnp.where(
                    dec, jnp.minimum(n_acc,
                                     jnp.maximum(n_emit - 1, 0)), 0)
                packed_out = jnp.concatenate(
                    [toks_all.astype(jnp.int32),
                     emit_all.astype(jnp.int32),
                     ctx_f[:, None].astype(jnp.int32),
                     act_f[:, None].astype(jnp.int32),
                     committed[:, None].astype(jnp.int32),
                     nd_eff[:, None].astype(jnp.int32)], axis=1)
                return (packed_out, tok_f, ctx_f, act_f, key) \
                    + tuple(t._data for t in npools)

            return _apply_multi(
                fn, [ids_t, nq_t, last_t, tgt_t, nd_t, tok_t, ctx_t,
                     act_t, tbl_t, lim_t, eos_t, key_t] + list(pools),
                n_out=5 + len(pools))

        self._spec_fn = to_static(sstep)
        self._compiled.add(("spec", C, 1 + K))
        return self._spec_fn

    def _dispatch_spec_step(self):
        """Launch one SPECULATIVE unified step: stream prefill chunks
        exactly like :meth:`_dispatch_step`, and for every active
        decode slot with budget propose up to K draft tokens from the
        configured :class:`~.spec_decode.DraftSource`, clamped to
        ``limits - ctx - 1`` so every verify write stays inside the
        slot's allocated table row. Runs serially (dispatch → harvest)
        — see :meth:`run`."""
        B, C, K = self.num_slots, self.prefill_chunk, self._spec_k
        ids = np.zeros((B, C), np.int32)
        nq = np.zeros((B,), np.int32)
        last = np.zeros((B,), bool)
        tgt = np.zeros((B,), bool)
        nd = np.zeros((B,), np.int32)
        n_pre = 0
        for slot in range(B):
            if not self._prefilling[slot] or n_pre >= self.admit_batch:
                continue
            prm = self._slot_prompt[slot]
            off = int(self._prefill_off[slot])
            v = min(C, len(prm) - off)
            ids[slot, :v] = prm[off:off + v]
            nq[slot] = v
            last[slot] = off + v == len(prm)
            tgt[slot] = self._act_target[slot]
            n_pre += 1
        drafting = [s for s in range(B)
                    if self.active[s] and not self._prefilling[s]
                    and self.slot_req[s] is not None
                    and int(self.limits[s]) - int(self.ctx[s]) > 1]
        if drafting:
            drafts, counts = self._spec_source.propose(
                self, drafting, K)
            for s in drafting:
                c = min(int(counts[s]), K,
                        int(self.limits[s]) - int(self.ctx[s]) - 1)
                if c > 0:
                    ids[s, 1:1 + c] = drafts[s, :c]
                    nd[s] = c
        fn = self._unified_spec_static()
        self._seq += 1
        self._last_fetch_dispatch_seq = self._seq
        n_steps = 1 + K
        n_active = int(np.sum((self.active
                               & (self.limits > self._pred_ctx))
                              | (nq > 0)))
        _t_obs = time.perf_counter()
        self._stats.inc("chunks")
        self._stats.inc("unified_steps")
        self._stats.inc("chunk_slot_steps", B * n_steps)
        if n_pre:
            self._stats.inc("prefill_waves")
        self._stats.inc("active_slot_steps", n_active * n_steps)
        self._c_spec_steps.inc()
        from ..profiler.trace import get_tracer
        _tr = get_tracer()
        if _tr.enabled:
            _tr.counter("serving/active_slots", n_active,
                        queued=len(self.queue), chunk_len=n_steps,
                        prefilling=n_pre)
        _frec.record_event("sched_turn", seq=self._seq, mode="spec",
                           active=n_active, queued=len(self.queue),
                           prefilling=n_pre, chunk_len=n_steps)
        self._obs_s += time.perf_counter() - _t_obs
        res = fn(Tensor(jnp.asarray(ids)), Tensor(jnp.asarray(nq)),
                 Tensor(jnp.asarray(last)), Tensor(jnp.asarray(tgt)),
                 Tensor(jnp.asarray(nd)),
                 Tensor(self._dev_tok), Tensor(self._dev_ctx),
                 Tensor(self._dev_act), Tensor(self._dev_tbl),
                 Tensor(self._dev_lim), Tensor(self._dev_eos),
                 Tensor(self._key), *self.pools)
        packed, tok_f, ctx_f, act_f, key_f = res[:5]
        self.pools = list(res[5:])
        self._dev_tok = tok_f._data
        self._dev_ctx = ctx_f._data
        self._dev_act = act_f._data
        self._key = key_f._data
        emits = np.zeros((B,), bool)
        for slot in range(B):
            if nq[slot] > 0:
                self._prefill_off[slot] += nq[slot]
                if last[slot]:
                    req = self.slot_req[slot]
                    tl = len(self._slot_prompt[slot])
                    req.t_prefill_done = time.perf_counter()
                    self._prefilling[slot] = False
                    self.ctx[slot] = tl
                    self.active[slot] = bool(tgt[slot])
                    self._act_since[slot] = self._seq
                    # the spec step has NO in-program decode tail:
                    # exactly the first token lands this turn
                    self._pred_ctx[slot] = tl
                    self._pc_insert(slot)
                    emits[slot] = True
            elif self.active[slot] \
                    and self.limits[slot] > self._pred_ctx[slot]:
                # at least the target sample always lands; the exact
                # accepted length arrives with the harvest mirrors
                self._pred_ctx[slot] = min(
                    int(self.limits[slot]),
                    int(self._pred_ctx[slot]) + 1)
                emits[slot] = True
        self._emits_inflight += emits.astype(np.int32)
        return (packed, list(self.slot_req), emits, n_steps, self._seq)

    def gauges(self) -> dict:
        """Serving observability surface (profiler subsystem):

        - ``slot_occupancy``: emitted tokens / dispatched slot-steps —
          the fraction of compiled slot-steps that produced a token.
        - ``active_occupancy``: slots active at dispatch / all slots —
          the drain/re-admit idle share specifically.
        - ``prefill_overlap_frac``: admissions made while a decode chunk
          was in flight (prefill waves then overlap its on-device run).
        - ``tokens_per_s``: emitted tokens / wall seconds inside run().
        - ``ttft_ms_p50/p99``: request-arrival → first-token-on-host
          percentiles (completed requests).
        - ``itl_ms_p50/p99``: smoothed inter-token latency percentiles —
          (t_done - t_first) / (tokens - 1) per request with ≥2 tokens.
        - ``compiled_programs``: distinct compiled signatures this
          engine built — steady-state 1 in unified mode (the single
          batching-step program); 1 prefill + the decode-chunk-length
          ladder in legacy mode. The compile-budget CI gate asserts on
          this.
        - ``chunks_empty``: harvested programs that delivered no
          tokens (unpredictable eos stops; structurally-wasted drain
          wave dispatches are eliminated).
        - ``prefill_waves``: programs that carried prompt tokens (in
          unified mode, unified steps with ≥1 prefilling slot).
        - ``unified_steps``: unified batching-step programs dispatched
          (0 in legacy mode).
        """
        s = self._stats.as_dict()
        steps = s["chunk_slot_steps"]
        return {
            "slot_occupancy": s["tokens_emitted"] / steps if steps
            else 0.0,
            "active_occupancy": s["active_slot_steps"] / steps if steps
            else 0.0,
            "prefill_overlap_frac": (s["prefills_overlapped"]
                                     / s["prefills"]) if s["prefills"]
            else 0.0,
            "tokens_per_s": (s["tokens_emitted"] / s["run_seconds"])
            if s["run_seconds"] else 0.0,
            "ttft_ms_p50": self._h_ttft.percentile(50),
            "ttft_ms_p99": self._h_ttft.percentile(99),
            "itl_ms_p50": self._h_itl.percentile(50),
            "itl_ms_p99": self._h_itl.percentile(99),
            "compiled_programs": len(self._compiled),
            "chunks_dispatched": s["chunks"],
            "chunks_empty": s["chunks_empty"],
            "prefill_waves": s["prefill_waves"],
            "unified_steps": s["unified_steps"],
            "tokens_emitted": s["tokens_emitted"],
            "prefills": s["prefills"],
            "requests_completed": s["requests_completed"],
            "obs_overhead_frac": (self._obs_s / s["run_seconds"])
            if s["run_seconds"] else 0.0,
            # reliability surface (ISSUE 10): overload economics
            "preempt_evictions": s["preempt_evictions"],
            "preempt_recompute_tokens": s["preempt_recompute_tokens"],
            "requests_cancelled": s["requests_cancelled"],
            "deadline_expired": (s["deadline_ttft_expired"]
                                 + s["deadline_total_expired"]),
            "shed_rejections": s["shed_rejections"],
            "queue_depth": len(self.queue),
            "quarantined": s["quarantined"],
            "containments": s["containments"],
            # prefix-cache economics (ISSUE 12): the shared-prefix
            # capacity story — hit rate, prefill tokens skipped, COW
            # forks and LRU evictions, plus current residency
            "prefix_cache_hits": s["prefix_cache_hits"],
            "prefix_cache_misses": s["prefix_cache_misses"],
            "prefix_cache_hit_rate": (
                s["prefix_cache_hits"]
                / (s["prefix_cache_hits"] + s["prefix_cache_misses"]))
            if s["prefix_cache_hits"] + s["prefix_cache_misses"]
            else 0.0,
            "prefix_cache_tokens_saved": s["prefix_cache_tokens_saved"],
            "prefix_cache_evictions": s["prefix_cache_evictions"],
            "prefix_cache_cow_forks": s["prefix_cache_cow_forks"],
            "prefix_cache_pages": len(self._pc_nodes),
            # speculative decoding economics (ISSUE 18)
            "spec_steps": int(self._c_spec_steps.value),
            "spec_tokens_drafted": int(self._c_spec_drafted.value),
            "spec_tokens_accepted": int(self._c_spec_accepted.value),
            "spec_tokens_rejected": int(self._c_spec_rejected.value),
            "spec_accept_rate": (
                self._c_spec_accepted.value
                / self._c_spec_drafted.value)
            if self._c_spec_drafted.value else 0.0,
            # quantized-KV pool geometry (ISSUE 20) — static per
            # engine, surfaced so capacity A/Bs read the byte budget
            # they actually ran at
            "kv_quant_bits": int(self._kv_quant_bits),
            "kv_quant_pool_bytes": int(self._kv_pool_bytes),
            "kv_quant_scale_pool_bytes": int(self._kv_scale_pool_bytes),
        }

    def reset_gauges(self):
        """Zero the gauge counters (e.g. after a warmup run whose lazy
        compiles would otherwise pollute tokens_per_s). The compiled-
        signature set is NOT cleared — compiled programs persist on the
        engine, so the compile-budget counter stays truthful."""
        for k in self._stats:
            self._stats[k] = 0.0 if k == "run_seconds" else 0
        for c in (self._c_spec_steps, self._c_spec_drafted,
                  self._c_spec_accepted, self._c_spec_rejected):
            c.set(0)
        self._h_ttft.reset()
        self._h_itl.reset()
        self._obs_s = 0.0

    def _emit_gauges(self):
        _t_obs = time.perf_counter()
        s = self._stats.as_dict()
        self._g_overhead.set(
            (self._obs_s / s["run_seconds"]) if s["run_seconds"]
            else 0.0)
        self._g_pc_pages.set(len(self._pc_nodes))
        self._g_queue_depth.set(len(self.queue))
        self._g_kvq_bits.set(int(self._kv_quant_bits))
        self._g_kvq_pool_bytes.set(int(self._kv_pool_bytes))
        self._g_kvq_scale_bytes.set(int(self._kv_scale_pool_bytes))
        from ..profiler.trace import get_tracer
        tr = get_tracer()
        if tr.enabled:
            for name, val in self.gauges().items():
                tr.counter(f"serving/{name}",
                           round(val, 6) if isinstance(val, float)
                           else val)
        self._obs_s += time.perf_counter() - _t_obs

    # ---- admission / chunked batched prefill -----------------------------

    def _alloc_pages(self, n):
        if len(self._free_pages) < n and self._pc_nodes:
            # allocation pressure: reclaim unreferenced cache pages
            # (refcount-aware LRU) before declaring scarcity — a warm
            # cache must never deny admission the cold pool would
            # grant. The shortfall counts pages already deferred
            # behind the in-flight harvest (including this method's
            # own earlier evictions): they WILL arrive, so evicting
            # more cache for the same request would just destroy warm
            # entries a pipeline-depth wait is about to make moot.
            deferred = sum(len(p) for _, p in self._deferred_free)
            short = n - len(self._free_pages) - deferred
            if short > 0:
                self._pc_evict(short)
        if len(self._free_pages) < n:
            return None
        return [self._free_pages.popleft() for _ in range(n)]

    def _release_pages(self, pages, safe=False):
        """Return pages to the free pool. ``safe=True`` (the drain
        path) frees immediately — a drained slot is already inactive in
        every dispatched program, so its writes are trash-page-guarded.
        Pages from an EVICTED (still device-active) slot are deferred
        until every fetched program dispatched so far has been
        harvested (see ``_deferred_free``)."""
        if not pages:
            return
        if safe or self._last_harvest_seq >= \
                self._last_fetch_dispatch_seq:
            self._free_pages.extend(pages)
        else:
            self._deferred_free.append(
                (self._last_fetch_dispatch_seq, list(pages)))

    def _release_deferred(self):
        """Move deferred pages whose gating program has been harvested
        back into the free pool (called from every harvest)."""
        if not self._deferred_free:
            return
        keep = []
        for gate, pages in self._deferred_free:
            if gate <= self._last_harvest_seq:
                self._free_pages.extend(pages)
            else:
                keep.append((gate, pages))
        self._deferred_free = keep

    def _audit_pages(self, where):
        """PADDLE_TPU_SERVING_AUDIT invariant, extended to shared
        pages (ISSUE 12): every page lives in exactly one place — the
        free list, an occupied slot's PRIVATE list, the prefix-cache
        index (refcount-unique: one physical page per node, however
        many slots read it), the deferred-reclamation set, or the
        reserved trash page 0 — and every cache node's refcount equals
        its live slot attachments (>= 1 for every referenced page, 0
        exactly for evictable residents; free-list pages have no node
        at all)."""
        if not self._audit:
            return
        held = [p for pages in self.slot_pages for p in pages]
        cached = list(self._pc_nodes)
        deferred = [p for _, pages in self._deferred_free
                    for p in pages]
        allp = list(self._free_pages) + held + cached + deferred
        if len(allp) + 1 != self.num_pages \
                or len(set(allp)) != len(allp) or 0 in allp:
            raise AssertionError(
                f"serving page accounting broken at {where}: "
                f"free={len(self._free_pages)} held={len(held)} "
                f"cached={len(cached)} deferred={len(deferred)} "
                f"(+1 trash) != {self.num_pages} pages, "
                f"dupes={len(allp) - len(set(allp))}, "
                f"trash_leaked={0 in allp}")
        refs: dict[int, int] = {}
        for nodes in self.slot_shared:
            for node in nodes:
                refs[node.page] = refs.get(node.page, 0) + 1
        # a migrated-out request's exported prefix stays pinned until
        # the destination acks (ISSUE 17): each pin is a live
        # attachment exactly like a reading slot
        for nodes in self._exported_pins.values():
            for node in nodes:
                refs[node.page] = refs.get(node.page, 0) + 1
        for node in self._pc_nodes.values():
            expect = refs.get(node.page, 0)
            if node.ref != expect or node.ref < 0:
                raise AssertionError(
                    f"prefix-cache refcount broken at {where}: page "
                    f"{node.page} ref={node.ref} but {expect} live "
                    f"attachment(s)")
            if node.parent is not self._pc_root \
                    and node.parent.ref < node.ref:
                raise AssertionError(
                    f"prefix-cache chain broken at {where}: page "
                    f"{node.page} ref={node.ref} exceeds parent page "
                    f"{node.parent.page} ref={node.parent.ref}")
        for page in refs:
            if page not in self._pc_nodes:
                raise AssertionError(
                    f"prefix-cache attachment to unindexed page "
                    f"{page} at {where}")
        # quantized-KV structural invariant (ISSUE 20): every layer
        # carries [k, v, k_scales, v_scales] and the scales pools index
        # the SAME page axis as their data pools — a page id is valid
        # in all four or in none, so the single accounting above covers
        # the scales pools too iff the geometry agrees
        if self.kv_quant != "none":
            if len(self.pools) != self._n_pools \
                    or self._n_pools != 4 * self.cfg.num_hidden_layers:
                raise AssertionError(
                    f"quantized pool count broken at {where}: "
                    f"{len(self.pools)} pools, expected "
                    f"{4 * self.cfg.num_hidden_layers}")
            for i, p in enumerate(self.pools):
                shape = tuple(p._data.shape)
                want = (self._pool_shape if i % 4 < 2
                        else self._scale_shape)
                if shape != want:
                    raise AssertionError(
                        f"quantized pool geometry broken at {where}: "
                        f"pool {i} shape {shape} != {want}")
                if i % 4 >= 2 and p._data.dtype != jnp.float32:
                    raise AssertionError(
                        f"scales pool {i} dtype {p._data.dtype} at "
                        f"{where}: scales must stay f32")
                if shape[1] != self.num_pages:
                    raise AssertionError(
                        f"pool {i} page-axis length {shape[1]} != "
                        f"num_pages {self.num_pages} at {where}")

    # ---- prefix cache: radix index + COW sharing (ISSUE 12) --------------

    def _pc_match(self, eff):
        """Longest cached full-page prefix of the admission prompt:
        walk the radix index block by block (``page_size`` tokens per
        level). Returns the matched node chain, root excluded."""
        if not self._prefix_cache:
            return []
        nodes, cur, ps = [], self._pc_root, self.page_size
        for i in range(len(eff) // ps):
            child = cur.children.get(eff[i * ps:(i + 1) * ps].tobytes())
            if child is None:
                break
            nodes.append(child)
            cur = child
        return nodes

    def _pc_pin(self, nodes):
        """Incref a matched chain (attach / pin against eviction)."""
        self._pc_clock += 1
        for node in nodes:
            node.ref += 1
            node.stamp = self._pc_clock

    def _pc_unpin(self, nodes):
        self._pc_clock += 1
        for node in nodes:
            node.ref -= 1
            node.stamp = self._pc_clock

    def _pc_detach(self, slot):
        """Drop a slot's shared-page attachments (drain/evict): decref
        only — the pages stay resident in the index, evictable once
        unreferenced (that residency IS the cache)."""
        if self.slot_shared[slot]:
            self._pc_unpin(self.slot_shared[slot])
            self.slot_shared[slot] = []

    def _pc_insert(self, slot):
        """Publish a slot's full prompt pages into the radix index at
        prefill completion: ownership moves page-by-page from the
        slot's private list to new cache nodes (the slot stays
        attached as a reader, so the refcount starts at 1). A level
        another slot published first keeps this slot's duplicate page
        private (it dies at drain) — re-pointing a live block table
        mid-flight is never worth the race. Safe against the async
        dispatch: a later attacher's program consumes this program's
        output pools, so the writes are ordered by data dependency."""
        if not self._prefix_cache:
            return
        eff = self._slot_prompt[slot]
        ps = self.page_size
        shared = self.slot_shared[slot]
        cur = shared[-1] if shared else self._pc_root
        self._pc_clock += 1
        for lvl in range(len(shared), len(eff) // ps):
            if not self.slot_pages[slot]:
                break
            key = eff[lvl * ps:(lvl + 1) * ps].tobytes()
            if key in cur.children:
                break
            page = self.slot_pages[slot].pop(0)
            node = _PrefixCacheNode(key, page, cur)
            node.ref = 1
            node.stamp = self._pc_clock
            cur.children[key] = node
            self._pc_nodes[page] = node
            shared.append(node)
            cur = node

    def _pc_evictable(self):
        """Pages the LRU could reclaim right now (ref-0 nodes; the
        monotone refcount chain makes every one reachable leaf-first)."""
        return sum(1 for n in self._pc_nodes.values() if n.ref == 0)

    def _pc_evict(self, n_pages):
        """Reclaim up to ``n_pages`` from unreferenced cache entries,
        LRU-first among childless ref-0 nodes (leaves first — an
        interior node never outlives its children, keeping every
        root-contiguous chain matchable). Freed pages ride the same
        deferred-release discipline as any reclaimed page: an
        in-flight program dispatched while a since-drained reader was
        attached may still READ them, so they only re-enter the free
        list once every fetched program has been harvested."""
        import heapq
        freed = []
        # one snapshot + a heap instead of a rescan per victim: no
        # admission runs inside this call, so nodes only change state
        # through our own evictions — a parent joins the heap exactly
        # when its last child is freed
        heap = [(n.stamp, n.page) for n in self._pc_nodes.values()
                if n.ref == 0 and not n.children]
        heapq.heapify(heap)
        while heap and len(freed) < n_pages:
            _, page = heapq.heappop(heap)
            victim = self._pc_nodes.get(page)
            if victim is None or victim.ref or victim.children:
                continue
            del victim.parent.children[victim.key]
            del self._pc_nodes[page]
            freed.append(page)
            parent = victim.parent
            if parent is not self._pc_root and parent.ref == 0 \
                    and not parent.children:
                heapq.heappush(heap, (parent.stamp, parent.page))
        if freed:
            self._stats.inc("prefix_cache_evictions", len(freed))
            self._release_pages(freed)
        return len(freed)

    def _pc_cow(self, src, dst):
        """Copy-on-write fork: duplicate one physical page across
        every layer's k/v pool so ``dst`` becomes a private writable
        copy of the shared ``src``. Functional pool update — the copy
        chains after every dispatched program in the device stream,
        exactly like admission's table/ctx updates, so it reads the
        prefix owner's completed writes and is visible to every later
        program."""
        s, d = jnp.int32(src), jnp.int32(dst)
        self.pools = [Tensor(a) for a in _pc_copy_page(
            [p._data for p in self.pools], s, d)]
        self._stats.inc("prefix_cache_cow_forks")

    @property
    def prefix_cache_pages(self):
        """Physical pages currently owned by the prefix-cache index
        (referenced + evictable) — the tests' page-accounting term."""
        return len(self._pc_nodes)

    def reset_prefix_cache(self):
        """Drop every UNREFERENCED cache entry (the bench cold/warm
        A/B resets without rebuilding the engine and recompiling its
        programs). Referenced entries stay — their readers are live.
        Returns the number of pages reclaimed."""
        n = self._pc_evict(len(self._pc_nodes))
        self._audit_pages("reset_prefix_cache")
        return n

    def _admission_key(self, req):
        # higher priority first; FIFO (arrival time, then id) within a
        # priority class — preempted requests keep their original
        # arrival slot, so recompute does not lose their queue position
        return (-req.priority, req.t_arrive, req.request_id)

    def _next_candidate(self):
        if not self.queue:
            return None
        if not self._has_priorities:
            return self.queue[0]       # the historical FIFO contract
        return min(self.queue, key=self._admission_key)

    def _already_complete(self, req):
        """A replayed request that already holds its full stream (it
        died between harvest and drain, or a wedged slot never drained
        it) — complete it instead of re-admitting."""
        if not req.tokens:
            return False
        eos = req.eos_token_id
        return (eos is not None and req.tokens[-1] == eos) \
            or len(req.tokens) >= req.max_new_tokens

    def _complete_ok(self, req):
        """Normal completion bookkeeping shared by the drain pass and
        the already-complete replay path."""
        req.finished = True
        req.t_done = time.perf_counter()
        eos = req.eos_token_id
        req.finish_reason = "eos" if (
            eos is not None and req.tokens
            and req.tokens[-1] == eos) else "length"
        req.strikes = 0        # innocence proven by completion
        self._record_latency(req)
        self.completed.append(req)
        _t_obs = time.perf_counter()
        self._stats.inc("requests_completed")
        _frec.record_event("finish", req=req.request_id,
                           reason=req.finish_reason,
                           tokens=len(req.tokens))
        self._obs_s += time.perf_counter() - _t_obs

    def _finish_error(self, req, err):
        """Complete a request EXCEPTIONALLY: typed error attached,
        tokens already emitted kept, latency booked when a first token
        existed."""
        req.finished = True
        req.error = err
        req.t_done = time.perf_counter()
        # completion instrumentation rides the obs_overhead_frac
        # window, exactly like _complete_ok (_record_latency books its
        # own slice internally)
        _t_obs = time.perf_counter()
        if isinstance(err, RequestCancelled):
            req.finish_reason = "cancelled"
            self._stats.inc("requests_cancelled")
        elif isinstance(err, DeadlineExceeded):
            req.finish_reason = "deadline"
            self._stats.inc("deadline_ttft_expired"
                            if err.kind == "ttft"
                            else "deadline_total_expired")
        else:
            req.finish_reason = "quarantined"
            self._stats.inc("quarantined")
        _frec.record_event("finish_error", req=req.request_id,
                           reason=req.finish_reason,
                           tokens=len(req.tokens))
        self._obs_s += time.perf_counter() - _t_obs
        self._record_latency(req)
        self.completed.append(req)
        return req

    def _clear_slot(self, slot, device=False):
        """The ONE per-slot teardown (drain and eviction share it —
        a field missed in a second copy is exactly the stale-state bug
        class the identity checks exist to catch). ``device=True``
        additionally deactivates the slot's DEVICE mirrors: needed on
        eviction, where the device still believes the slot is active;
        a drained slot already went inactive inside its program."""
        self._pc_detach(slot)        # shared pages: decref, stay cached
        self.slot_pages[slot] = []
        self.slot_req[slot] = None
        self._slot_prompt[slot] = None
        self.tables[slot] = 0
        self.ctx[slot] = 0
        self._pred_ctx[slot] = 0
        self.limits[slot] = 0
        self.slot_eos[slot] = -1
        self._prefill_off[slot] = 0
        self._act_target[slot] = False
        if device:
            self.active[slot] = False
            self._prefilling[slot] = False
            self._pending_first[slot] = False
            self._echo_inflight[slot] = False
            self._emits_inflight[slot] = 0
            self._dev_tbl = self._dev_tbl.at[slot].set(
                jnp.zeros((self.pages_per_slot,), jnp.int32))
            self._dev_act = self._dev_act.at[slot].set(False)
            self._dev_ctx = self._dev_ctx.at[slot].set(0)
            self._dev_lim = self._dev_lim.at[slot].set(0)
            self._dev_eos = self._dev_eos.at[slot].set(-1)

    def _evict_slot(self, slot, requeue, reason="preempt", error=None):
        """Tear one occupied slot out of the engine mid-flight:
        deactivate it on host AND device (an in-flight program's stale
        view of the slot is discarded at harvest via the slot_req
        identity check), reclaim its pages (deferred past any fetched
        program that could still write them), and either requeue the
        request for recompute-style re-prefill or complete it with a
        typed error."""
        req = self.slot_req[slot]
        if requeue:
            self._stats.inc("preempt_evictions")
            self._stats.inc("preempt_pages_reclaimed",
                            len(self.slot_pages[slot]))
        self._release_pages(self.slot_pages[slot])
        self._clear_slot(slot, device=True)
        _frec.record_event("preempt", slot=slot, req=req.request_id,
                           tokens=len(req.tokens), reason=reason)
        record_hop(req, "preempt" if requeue else "evict",
                   replica=getattr(self, "_fleet_replica_id", None),
                   reason=reason, tokens=len(req.tokens))
        if requeue:
            req.preemptions += 1
            self.queue.appendleft(req)
        elif error is not None:
            self._finish_error(req, error)
        return req

    def _preempt_for(self, req, need, need_slot=False):
        """vLLM-style recompute preemption: evict strictly-LOWER-
        priority occupants — lowest priority, youngest (latest admit)
        first — until ``req`` has a slot (when ``need_slot``) and
        ``need`` pages are available or provably arriving (deferred
        behind the in-flight harvest). Equal-priority traffic never
        preempts: pure overload queues, it does not thrash."""
        victims = [s for s in range(self.num_slots)
                   if self.slot_req[s] is not None
                   and self.slot_req[s].priority < req.priority]
        victims.sort(key=lambda s: (self.slot_req[s].priority,
                                    -self.slot_req[s].t_admit))
        projected = len(self._free_pages) + sum(
            len(p) for _, p in self._deferred_free) \
            + self._pc_evictable()
        # feasibility first: if evicting EVERY victim still cannot
        # reach ``need``, evict none — destroying in-flight progress
        # with no admission to show for it is pure waste
        if projected + sum(len(self.slot_pages[s])
                           for s in victims) < need:
            return False
        evicted = 0
        for s in victims:
            if projected >= need and (evicted or not need_slot):
                break
            projected += len(self.slot_pages[s])
            self._evict_slot(s, requeue=True, reason="preempt")
            evicted += 1
        if need_slot and not evicted:
            return False
        return projected >= need

    def _lifecycle_error(self, req, now):
        if req.cancelled:
            return RequestCancelled(req.request_id)
        if req.deadline_s is not None \
                and now - req.t_arrive > req.deadline_s:
            return DeadlineExceeded(req.request_id, "total",
                                    req.deadline_s)
        if req.ttft_deadline_s is not None and not req.t_first \
                and now - req.t_arrive > req.ttft_deadline_s:
            return DeadlineExceeded(req.request_id, "ttft",
                                    req.ttft_deadline_s)
        return None

    def _reap(self):
        """The lifecycle control point, once per scheduler turn:
        cancelled or deadline-expired requests are shed from the queue,
        running ones are evicted (pages reclaimed mid-prefill or
        mid-decode) — each completes with its typed error instead of
        silently occupying a slot."""
        done = []
        now = time.perf_counter()
        # O(queue) sweep gated on lifecycle control being in play; the
        # periodic sweep bounds how long a direct handle-cancel() of a
        # queued request can go unobserved. Running slots (few) are
        # always swept below.
        self._reap_turn += 1
        if self.queue and (self._lifecycle_seen
                           or self._reap_turn % 32 == 0):
            drop = [(req, err) for req in self.queue
                    if (err := self._lifecycle_error(req, now))
                    is not None]
            if drop:
                self._lifecycle_seen = True
            for req, err in drop:
                self.queue.remove(req)
                done.append(self._finish_error(req, err))
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None or req.finished:
                continue
            err = self._lifecycle_error(req, now)
            if err is not None:
                self._evict_slot(slot, requeue=False,
                                 reason=type(err).__name__,
                                 error=err)
                done.append(req)
        return done

    def _admit(self):
        """Move queued requests into free slots: allocate pages, stage
        per-slot state, and mark the slot PREFILLING — the prompt itself
        streams through the batched prefill-chunk program in
        :meth:`_pump_prefill`. Admission order is priority-then-FIFO;
        when no slot or not enough pages are free, a strictly-higher-
        priority candidate preempts running lower-priority sequences
        (:meth:`_preempt_for`). Requests implicated by a step failure
        (``strikes > 0``) re-enter SOLO so the next fault implicates
        exactly one request."""
        while self.queue:
            req = self._next_candidate()
            if self._already_complete(req):
                # replayed request whose stream was already complete
                self.queue.remove(req)
                self._complete_ok(req)
                self._done_pending.append(req)
                continue
            if any(r is not None and r.strikes for r in self.slot_req):
                return         # a suspect runs alone, nothing joins it
            occupied = any(r is not None for r in self.slot_req)
            if req.strikes and occupied:
                return         # suspects wait for an empty engine
            gen = len(req.tokens)
            remaining = req.max_new_tokens - gen
            eff_len = req.prompt.size + gen
            need_total = -(-(eff_len + remaining) // self.page_size)
            slot = next((s for s in range(self.num_slots)
                         if self.slot_req[s] is None
                         and not self.active[s]), None)
            if slot is None and not self._has_priorities:
                return   # no slot and nobody to preempt: skip the
                         # O(prompt) replay-concat + radix-match work
                         # this turn would throw away
            if gen:
                # recompute re-admission: prompt + generated tokens
                # stream back through prefill (token-identical replay)
                eff = np.concatenate(
                    [req.prompt,
                     np.asarray(req.tokens, np.int32)])
            else:
                eff = req.prompt
            # cached-prefix fast path (ISSUE 12): match BEFORE the
            # page-need computation — shared pages are attached, not
            # allocated, so a warm cache admits deeper than the cold
            # pool would. The match is PINNED (incref) before any
            # allocation so the LRU cannot reclaim it mid-admission.
            shared = self._pc_match(eff)
            # copy-on-write case: the WHOLE admission prompt is
            # cached, but at least the last token must re-prefill to
            # produce logits — its write lands inside the last shared
            # page, so that page is forked to a private copy
            cow = bool(shared) \
                and len(shared) * self.page_size >= len(eff)
            start = len(eff) - 1 if cow \
                else len(shared) * self.page_size
            need = need_total - len(shared) + (1 if cow else 0)
            self._pc_pin(shared)
            if slot is None:
                if not self._preempt_for(req, need, need_slot=True):
                    self._pc_unpin(shared)
                    return
                slot = next((s for s in range(self.num_slots)
                             if self.slot_req[s] is None
                             and not self.active[s]), None)
                if slot is None:
                    self._pc_unpin(shared)
                    return
            pages = self._alloc_pages(need)
            if pages is None and self._has_priorities \
                    and self._preempt_for(req, need):
                pages = self._alloc_pages(need)
            if pages is None:
                self._pc_unpin(shared)
                return   # reclaimed pages still deferred behind the
                         # in-flight harvest (or pure overload): the
                         # candidate stays queued, admit next turn
            attach = shared
            if cow:
                fork = shared[-1]
                self._pc_cow(fork.page, pages[0])
                self._pc_unpin([fork])
                attach = shared[:-1]
            if self._prefix_cache:
                self._stats.inc("prefix_cache_hits" if start
                                else "prefix_cache_misses")
                if start:
                    self._stats.inc("prefix_cache_tokens_saved", start)
            self.queue.remove(req)
            if gen:
                self._stats.inc("preempt_recompute_tokens", gen)
            self._stage_slot(slot, req, pages, eff, remaining,
                             attach=attach, start=start)
        return

    def _stage_slot(self, slot, req, pages, eff, remaining,
                    attach=(), start=0):
        """Bind an admitted request to a slot: block-table row, device
        mirrors, prefill progress. ``eff`` is the admission prompt
        (original prompt + recompute replay tokens), ``remaining`` the
        generation budget left. ``attach`` is the cached-prefix node
        chain (already pinned) whose pages head the block table;
        ``start`` is the cached prefix length in tokens — prefill
        resumes there, indistinguishable from a slot that already
        streamed ``start`` tokens (chunked prefill always supported
        arbitrary offsets; sharing only redirects the table)."""
        tl = len(eff)
        self.slot_pages[slot] = pages
        self.slot_shared[slot] = list(attach)
        self._slot_prompt[slot] = eff
        row = np.zeros((self.pages_per_slot,), np.int32)
        row[:len(attach)] = [n.page for n in attach]
        row[len(attach):len(attach) + len(pages)] = pages
        self.tables[slot] = row
        self._dev_tbl = self._dev_tbl.at[slot].set(jnp.asarray(row))
        req.t_admit = time.perf_counter()
        _t_obs = req.t_admit
        if self._trace_every:
            req.traced = req.request_id % self._trace_every == 0
        record_hop(req, "admit",
                   replica=getattr(self, "_fleet_replica_id", None),
                   slot=slot, cached=int(start),
                   replayed=len(req.tokens))
        self._stats.inc("prefills")
        if self._overlap_admission:
            self._stats.inc("prefills_overlapped")
        from ..profiler.trace import get_tracer
        _tr = get_tracer()
        if _tr.enabled:
            _tr.instant("serving/prefill", slot=slot, prompt_len=tl,
                        chunk=self.prefill_chunk,
                        overlapped=self._overlap_admission)
        _frec.record_event("admit", slot=slot,
                           req=req.request_id, prompt_len=tl,
                           cached=int(start), queued=len(self.queue))
        self._obs_s += time.perf_counter() - _t_obs
        self.slot_req[slot] = req
        self._prefilling[slot] = True
        self._prefill_off[slot] = start
        self._emits_inflight[slot] = 0
        # a prefill-role engine never activates decode: the slot
        # finishes its prompt, samples the first token in-program, and
        # goes inactive — the drain pass exports it for migration. A
        # no_migrate request (the fleet found no decode capacity)
        # decodes here like any colocated stream
        self._act_target[slot] = remaining > 1 \
            and (self.role != "prefill"
                 or getattr(req, "no_migrate", False))
        self.ctx[slot] = start
        self._pred_ctx[slot] = start
        self._dev_ctx = self._dev_ctx.at[slot].set(int(start))
        self.slot_eos[slot] = -1 if req.eos_token_id is None \
            else int(req.eos_token_id)
        # ctx counts CACHE entries; one generated token is always
        # pending outside the cache, so the n-th token lands when
        # ctx hits tl + n - 1 (not tl + n)
        self.limits[slot] = tl + remaining - 1
        self._dev_lim = self._dev_lim.at[slot].set(
            int(self.limits[slot]))
        self._dev_eos = self._dev_eos.at[slot].set(
            int(self.slot_eos[slot]))

    def _prefill_static(self):
        """The ONE compiled prefill signature: every wave — any mix of
        prompt lengths, any number of admitted prompts up to
        ``admit_batch`` — runs through this [num_slots, prefill_chunk]
        program. Writes pages incrementally, attends causally over the
        paged history, and samples the first token for slots whose
        prompt ends inside the chunk (it stays device-resident; the next
        decode chunk echoes it through the packed fetch)."""
        if self._prefill_fn is not None:
            return self._prefill_fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature
        C = self.prefill_chunk

        def prefill(ids_t, pstart_t, valid_t, last_t, tgt_t, tok_t,
                    ctx_t, act_t, tbl_t, key_t, *pools):

            def fn(ids, pstart, valid, last, tgt, tok, ctx, act, tbl,
                   key, *pool_leaves):
                with no_grad():
                    logits, npools = model(
                        Tensor(ids),
                        caches=[Tensor(a) for a in pool_leaves],
                        pos=Tensor(pstart[:, None]),
                        tables=(Tensor(tbl), Tensor(valid)))
                lg = logits._data                        # [B, C, V]
                idx = jnp.clip(valid - 1, 0, C - 1)
                last_lg = jnp.take_along_axis(
                    lg, idx[:, None, None], axis=1)[:, 0]
                last_lg = last_lg.astype(jnp.float32)    # [B, V]
                if greedy:
                    sampled = jnp.argmax(last_lg, -1).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    sampled = jax.random.categorical(
                        sub, last_lg / temperature).astype(jnp.int32)
                fire = last & (valid > 0)
                tok2 = jnp.where(fire, sampled, tok)
                ctx2 = ctx + valid
                act2 = jnp.where(fire, tgt, act)
                return (tok2, ctx2, act2, key) + tuple(
                    t._data for t in npools)

            return _apply_multi(
                fn, [ids_t, pstart_t, valid_t, last_t, tgt_t, tok_t,
                     ctx_t, act_t, tbl_t, key_t] + list(pools),
                n_out=4 + len(pools))

        self._prefill_fn = to_static(prefill)
        self._compiled.add(("prefill", C))
        return self._prefill_fn

    def _pump_prefill(self, max_waves=None):
        """Dispatch batched prefill-chunk programs until every
        prefilling slot has streamed its whole prompt (or ``max_waves``
        waves were dispatched — the interleaving throttle). Entirely
        async: no host fetch; completion is host-predicted (prompt
        lengths are known)."""
        B, C = self.num_slots, self.prefill_chunk
        waves = 0
        while self._prefilling.any():
            if max_waves is not None and waves >= max_waves:
                return
            ids = np.zeros((B, C), np.int32)
            pstart = np.zeros((B,), np.int32)
            valid = np.zeros((B,), np.int32)
            last = np.zeros((B,), bool)
            tgt = np.zeros((B,), bool)
            batched = []
            for slot in range(B):
                if not self._prefilling[slot]:
                    continue
                if len(batched) >= self.admit_batch:
                    continue      # next wave picks it up
                prm = self._slot_prompt[slot]
                off = int(self._prefill_off[slot])
                v = min(C, len(prm) - off)
                ids[slot, :v] = prm[off:off + v]
                pstart[slot] = off
                valid[slot] = v
                last[slot] = off + v == len(prm)
                tgt[slot] = self._act_target[slot]
                batched.append(slot)
            fn = self._prefill_static()
            self._seq += 1
            self._stats["prefill_waves"] += 1
            res = fn(Tensor(jnp.asarray(ids)), Tensor(jnp.asarray(pstart)),
                     Tensor(jnp.asarray(valid)), Tensor(jnp.asarray(last)),
                     Tensor(jnp.asarray(tgt)), Tensor(self._dev_tok),
                     Tensor(self._dev_ctx), Tensor(self._dev_act),
                     Tensor(self._dev_tbl), Tensor(self._key),
                     *self.pools)
            tok2, ctx2, act2, key2 = res[:4]
            self.pools = list(res[4:])
            self._dev_tok = tok2._data
            self._dev_ctx = ctx2._data
            self._dev_act = act2._data
            self._key = key2._data
            for slot in batched:
                self._prefill_off[slot] += valid[slot]
                if not last[slot]:
                    continue
                # final wave for this prompt: host-side activation —
                # the sampled first token stays on device and is echoed
                # through the next decode chunk's packed fetch (or the
                # drain-time fetch for one-shot tail requests)
                req = self.slot_req[slot]
                tl = len(self._slot_prompt[slot])
                req.t_prefill_done = time.perf_counter()
                self._prefilling[slot] = False
                self.ctx[slot] = tl
                self._pred_ctx[slot] = tl
                self._pending_first[slot] = True
                self._act_since[slot] = self._seq
                # instant-eos (first token == stop token) is detected ON
                # DEVICE at the next chunk's entry; only the structural
                # one-token case is known host-side now
                self.active[slot] = bool(self._act_target[slot])
                # prompt pages final: publish for prefix sharing
                self._pc_insert(slot)
            waves += 1

    # ---- chunked decode --------------------------------------------------

    def _worth_dispatching(self):
        """Is there any slot a decode chunk could advance? With the
        host's ctx prediction this is exact for length-limited slots, so
        the structurally-wasted drain-wave dispatch never happens; an
        eos stop the host cannot see may still yield an empty chunk
        (counted in ``chunks_empty``)."""
        return bool(np.any(self.active & (self.limits > self._pred_ctx)))

    def _next_chunk_len(self):
        """Adaptive chunk length: clamp to the minimum predicted
        remaining budget across active slots so no slot oversteps its
        limit inside a chunk, quantized to a power-of-two ladder ≤
        ``decode_chunk`` to bound distinct compiled signatures."""
        if not self.adaptive_chunk:
            return self.decode_chunk
        rem = (self.limits - self._pred_ctx)[self.active
                                             & (self.limits
                                                > self._pred_ctx)]
        if rem.size == 0:
            return self.decode_chunk
        m = int(rem.min())
        if m >= self.decode_chunk:
            return self.decode_chunk
        return 1 << (m.bit_length() - 1)

    def _chunk_static(self, n_steps):
        fn = self._chunk_fns.get(n_steps)
        if fn is not None:
            return fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature

        def chunk(tok_t, ctx_t, act_t, tbl_t, lim_t, eos_t, key_t,
                  *pools):
            fwd = model.forward

            def fn(tok, ctx, act, tbl, lim, eos_arr, key, *pool_leaves):
                b = tok.shape[0]
                # a freshly admitted slot whose prefill token already hit
                # its stop token must not decode (the host never saw the
                # token — instant-eos is detected here, on device)
                act = act & ((eos_arr < 0) | (tok != eos_arr))
                init_tok = tok

                def body(carry, _):
                    tok_c, ctx_c, act_c, key_c, leaves = carry
                    with no_grad():
                        logits, ncaches = fwd(
                            Tensor(tok_c.reshape(b, 1)),
                            caches=[Tensor(a) for a in leaves],
                            pos=Tensor(ctx_c[:, None]),
                            tables=(Tensor(tbl), Tensor(act_c)))
                    lg = logits[:, -1]._data.astype(jnp.float32)
                    if greedy:
                        nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                    else:
                        key_c, sub = jax.random.split(key_c)
                        nxt = jax.random.categorical(
                            sub, lg / temperature).astype(jnp.int32)
                    ctx_n = ctx_c + act_c.astype(jnp.int32)
                    nxt = jnp.where(act_c, nxt, tok_c)
                    # per-slot eos (a traced [B] array, -1 = none): each
                    # request may carry its own stop token
                    still = act_c & (ctx_n < lim) & \
                        ((eos_arr < 0) | (nxt != eos_arr))
                    new_leaves = tuple(t._data for t in ncaches)
                    out_tok = jnp.where(act_c, nxt, -1)
                    return (nxt, ctx_n, still, key_c, new_leaves), \
                        (out_tok, act_c)

                carry0 = (tok, ctx, act, key, tuple(pool_leaves))
                carry, (toks, emitted) = jax.lax.scan(
                    body, carry0, jnp.arange(n_steps))
                tok_f, ctx_f, act_f, key_f, leaves_f = carry
                # ONE packed int32 fetch carries everything the host
                # scheduler needs: emitted tokens, emission mask, the
                # first-token echo for freshly admitted slots, and the
                # ctx/active mirrors
                packed_out = jnp.concatenate(
                    [toks.T.astype(jnp.int32),
                     emitted.T.astype(jnp.int32),
                     init_tok[:, None].astype(jnp.int32),
                     ctx_f[:, None].astype(jnp.int32),
                     act_f[:, None].astype(jnp.int32)], axis=1)
                return (packed_out, tok_f, ctx_f, act_f, key_f) \
                    + tuple(leaves_f)

            return _apply_multi(fn, [tok_t, ctx_t, act_t, tbl_t, lim_t,
                                     eos_t, key_t]
                                + list(pools), n_out=5 + len(pools))

        fn = to_static(chunk)
        self._chunk_fns[n_steps] = fn
        self._compiled.add(("chunk", n_steps))
        return fn

    def _dispatch_chunk(self):
        """Launch one chunk program (async) and chain the device state.
        Returns an in-flight record for :meth:`_harvest_chunk` — the
        packed output is NOT fetched here, so a caller may overlap the
        fetch with the next chunk's on-device compute."""
        n = self._next_chunk_len()
        fn = self._chunk_static(n)
        self._seq += 1
        self._last_fetch_dispatch_seq = self._seq
        # "active" for occupancy accounting = slots this chunk can
        # actually advance (host-active AND budget remaining); a slot
        # that exhausted its budget but has not drained yet is idle
        n_active = int(np.sum(self.active
                              & (self.limits > self._pred_ctx)))
        _t_obs = time.perf_counter()
        self._stats.inc("chunks")
        self._stats.inc("chunk_slot_steps", self.num_slots * n)
        self._stats.inc("active_slot_steps", n_active * n)
        from ..profiler.trace import get_tracer
        _tr = get_tracer()
        if _tr.enabled:
            _tr.counter("serving/active_slots", n_active,
                        queued=len(self.queue), chunk_len=n)
        _frec.record_event("sched_turn", seq=self._seq, mode="legacy",
                           active=n_active, queued=len(self.queue),
                           chunk_len=n)
        self._obs_s += time.perf_counter() - _t_obs
        res = fn(Tensor(self._dev_tok), Tensor(self._dev_ctx),
                 Tensor(self._dev_act), Tensor(self._dev_tbl),
                 Tensor(self._dev_lim), Tensor(self._dev_eos),
                 Tensor(self._key), *self.pools)
        packed, tok_f, ctx_f, act_f, key_f = res[:5]
        self.pools = list(res[5:])
        self._dev_tok = tok_f._data
        self._dev_ctx = ctx_f._data
        self._dev_act = act_f._data
        self._key = key_f._data
        self._pred_ctx = np.where(
            self.active,
            np.minimum(self.limits, self._pred_ctx + n),
            self._pred_ctx).astype(np.int32)
        # snapshot the slot->request mapping, the pending-first mask and
        # the dispatch seq: by harvest time a drained slot may have been
        # re-admitted (or a prefilling slot activated) — stale views
        # must not be applied
        rec = (packed, list(self.slot_req), self._pending_first.copy(),
               n, self._seq)
        self._echo_inflight |= self._pending_first
        self._pending_first[:] = False
        return rec

    def _harvest_chunk(self, rec):
        """Fetch one in-flight chunk's packed output and apply it."""
        packed, snap_req, pending, n, seq = rec
        arr = np.asarray(packed._data)            # the ONE fetch
        self._last_harvest_seq = max(self._last_harvest_seq, seq)
        self._release_deferred()
        toks_np = arr[:, :n]
        emitted_np = arr[:, n:2 * n].astype(bool)
        init_tok = arr[:, 2 * n]
        ctx_m = arr[:, 2 * n + 1].astype(np.int32)
        act_m = arr[:, 2 * n + 2].astype(bool)
        t_now = time.perf_counter()
        appended = 0
        for slot in range(self.num_slots):
            req = snap_req[slot]
            if req is not self.slot_req[slot]:
                # slot evicted (its echo flag was reset by the
                # eviction) or re-admitted since this dispatch: the
                # stale pending snapshot must not clear the NEW
                # occupant's first-token guard — its token rides a
                # later, unharvested program
                continue
            if pending[slot]:
                # this harvest delivers the slot's first-token echo;
                # _drain may finish the slot again from here on
                self._echo_inflight[slot] = False
            if self._act_since[slot] <= seq:
                # the chunk's view of this slot is current (it was not
                # re-activated by a prefill wave after this dispatch)
                self.ctx[slot] = ctx_m[slot]
                self.active[slot] = act_m[slot]
            if req is None:
                continue
            if pending[slot]:
                if not req.tokens:
                    req.t_first = t_now
                req.tokens.append(int(init_tok[slot]))
                appended += 1
            if req.finished:
                continue
            req.strikes = 0        # clean harvest exonerates (above)
            for j in range(n):
                if emitted_np[slot, j]:
                    if not req.tokens:
                        req.t_first = t_now
                    req.tokens.append(int(toks_np[slot, j]))
                    appended += 1
        _t_obs = time.perf_counter()
        self._stats.inc("tokens_emitted", appended)
        if appended == 0:
            self._stats.inc("chunks_empty")
        self._obs_s += time.perf_counter() - _t_obs

    def _decode_chunk(self):
        self._harvest_chunk(self._dispatch_chunk())

    # ---- completion ------------------------------------------------------

    def _record_latency(self, req):
        """Book a finished request's latency into the bounded
        reservoirs and, for sampled requests, reconstruct its
        lifecycle spans into the chrome trace (queued → admitted →
        prefill → first-token → decode → finished) from the stamps
        taken on the hot path. Counted in the ``obs_overhead_frac``
        self-measurement window (the observes and the trace
        reconstruction ARE instrumentation cost)."""
        _t_obs = time.perf_counter()
        if req.t_first:
            self._h_ttft.observe((req.t_first - req.t_arrive) * 1e3)
            if len(req.tokens) > 1:
                self._h_itl.observe(
                    (req.t_done - req.t_first) * 1e3
                    / (len(req.tokens) - 1))
        record_hop(req, "finish",
                   replica=getattr(self, "_fleet_replica_id", None),
                   reason=req.finish_reason, tokens=len(req.tokens))
        if req.trace_id is None and req.request_id >= 0:
            # standalone engine use: THIS is the end of the request's
            # timeline, so feed the process trace log here. A
            # fleet-managed request (trace_id minted by the router) is
            # fed by the fleet at DELIVERY instead — a replica
            # completion may only be the losing hedge copy. Negative
            # ids are sacrificial warmup requests (fleet._warm): they
            # deliberately absorb the XLA compile, and their
            # multi-second "latency" would otherwise dominate the
            # /statusz slowest-traces render
            _get_trace_log().record(request_trace_summary(req))
        if req.traced:
            self._emit_request_trace(req)
        self._obs_s += time.perf_counter() - _t_obs

    def _emit_request_trace(self, req):
        from ..profiler.trace import get_tracer
        tr = get_tracer()
        if not tr.enabled:
            return
        rid = int(req.request_id)
        # each traced request gets its own track (tid) so Perfetto
        # shows the lifecycle as one stacked lane per request; a
        # fleet-minted trace id (ISSUE 13) keeps every attempt —
        # preemption replays, failover re-admissions, hedge copies —
        # on ONE track, reconstructing the cross-replica timeline
        tid = int(req.trace_id) if req.trace_id is not None else rid
        admit = req.t_admit or req.t_arrive
        tr.complete("req/queued", req.t_arrive, admit,
                    cat="serving_req", tid=tid, request_id=rid)
        pre_end = req.t_prefill_done or req.t_first or admit
        tr.complete("req/prefill", admit, pre_end, cat="serving_req",
                    tid=tid, prompt_len=int(len(req.prompt)))
        if req.t_first:
            tr.complete("req/first_token_wait", pre_end, req.t_first,
                        cat="serving_req", tid=tid)
            tr.complete("req/decode", req.t_first, req.t_done,
                        cat="serving_req", tid=tid,
                        tokens=len(req.tokens))
        if req.trace_id is None:
            # hop markers: zero-length retroactive spans AT the hop
            # timestamps, so the timeline places preemptions where
            # they happened. Fleet-owned traces (trace_id set) get
            # their hop markers from the fleet's delivery-time
            # reconstruction instead — emitting here too would
            # duplicate every marker on the same track
            for h in req.hops or ():
                tr.complete("req/hop", h["t"], h["t"],
                            cat="serving_req", tid=tid,
                            **{**h, "request_id": rid})
        tr.instant("req/finished", cat="serving_req",
                   request_id=rid, reason=req.finish_reason,
                   tokens=len(req.tokens))

    def _drain(self):
        # lifecycle first: cancellations and deadline expiries free
        # their pages and complete with typed errors at this turn
        done = self._reap()
        if self._done_pending:
            done.extend(self._done_pending)
            self._done_pending = []
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None:
                continue
            if self._prefilling[slot]:
                # prompt still streaming through prefill waves — the
                # slot is inactive but very much occupied
                continue
            if self._echo_inflight[slot] or self._emits_inflight[slot]:
                # tokens for this slot ride a dispatched-but-
                # unharvested program: finishing now would lose them
                # (defer one loop)
                continue
            if not self.active[slot]:
                if self._pending_first[slot]:
                    # finished without any chunk running after prefill
                    # completion (one-token request at the tail of the
                    # workload): the first token never got echoed —
                    # fetch it now
                    req.t_first = time.perf_counter()
                    req.tokens.append(int(np.asarray(
                        self._dev_tok[slot])))
                    self._stats.inc("tokens_emitted")
                    self._pending_first[slot] = False
                if self._should_migrate(slot, req):
                    self._migrate_out(slot, req)
                    continue
                finished_now = not req.finished
                # drained slots are inactive in every dispatched
                # program (writes trash-page-guarded), so their pages
                # are immediately reusable
                self._release_pages(self.slot_pages[slot], safe=True)
                self._clear_slot(slot)
                if finished_now:
                    self._complete_ok(req)
                done.append(req)
        self._audit_pages("drain")
        return done


def _apply_multi(fn, tensors, n_out):
    """apply() with a tuple return of n_out arrays."""
    from ..framework.core import apply
    return apply(fn, *tensors, n_outputs=n_out, differentiable=False,
                 name="serving_engine")


# -- tunable surface ---------------------------------------------------------
# The engine's chunk ladder is a tunable surface like the kernel tiles,
# but its trial needs a whole engine + workload, so there is no
# standalone builder: `bench.py --autotune`'s cb section is the sweep
# vehicle (it times candidate ladders on the real workload and commits
# the winner); a recorded winner then serves every ctor call that
# leaves the knobs as None. Candidate values are powers of two — the
# adaptive decode ladder and the compiled-signature budget both
# assume pow2.

def _register_serving_surface():
    from ..tuner.surface import TunableSurface, register_surface

    def _candidates(shape):
        slots = int(shape.get("slots", 4))
        max_len = int(shape.get("max_len", 512))
        out = []
        for dc in (8, 16, 32, 64):
            if dc > max_len:
                continue
            for pc in (32, 64, 128, 256):
                if pc > max_len:
                    continue
                for ab in sorted({1, max(slots // 2, 1), slots}):
                    out.append({"decode_chunk": dc, "prefill_chunk": pc,
                                "admit_batch": ab})
        return out

    def _is_valid(config, shape):
        slots = int(shape.get("slots", 4))
        max_len = int(shape.get("max_len", 512))
        return (1 <= config["decode_chunk"] <= max_len
                and 1 <= config["prefill_chunk"] <= max_len
                and 1 <= config["admit_batch"] <= slots)

    register_surface(TunableSurface(
        name="serving_chunks",
        params=("decode_chunk", "prefill_chunk", "admit_batch"),
        default={"decode_chunk": 16, "prefill_chunk": 128,
                 "admit_batch": 4},
        candidates=_candidates,
        is_valid=_is_valid,
        describe="ContinuousBatchingEngine ladder: decode chunk length, "
                 "batched-prefill chunk, prompts admitted per prefill "
                 "wave. Shape key: slots/max_len/page."))


def _register_spec_surface():
    from ..tuner.surface import TunableSurface, register_surface

    def _candidates(shape):
        max_len = int(shape.get("max_len", 512))
        out = []
        for k in (2, 4, 6, 8):
            if k + 1 > max_len:
                continue
            for src in ("ngram", "self"):
                out.append({"k": k, "source": src})
        return out

    def _is_valid(config, shape):
        max_len = int(shape.get("max_len", 512))
        return (1 <= int(config["k"]) < max_len
                and config["source"] in ("ngram", "self"))

    register_surface(TunableSurface(
        name="spec_decode",
        params=("k", "source"),
        default={"k": 4, "source": "ngram"},
        candidates=_candidates,
        is_valid=_is_valid,
        describe="Speculative decoding: draft tokens per decode slot "
                 "(K, verified as a length-K+1 ragged chunk) x draft "
                 "source ('ngram' prompt-lookup / 'self' skip-layer). "
                 "Shape key: slots/max_len/page — the cb geometry; "
                 "bench.py --autotune's cb-spec section is the sweep "
                 "vehicle."))


_register_serving_surface()
_register_spec_surface()
