"""Continuous-batching LLM serving engine over paged KV caches.

Reference role: the serving layer PaddleNLP/FastDeploy put on top of
Paddle Inference (dynamic batching + paged/ragged KV attention for mixed-
length streams; reference mount empty, no cites — SURVEY.md §2.1
inference row, PAPERS.md ragged-paged-attention).

TPU-native design — the vLLM recipe restructured for XLA's static-shape
world:

- The KV cache is a global PAGE POOL per layer ([KVH, num_pages,
  page_size, D]); each admitted request owns a page list (its block
  table row). Page 0 is a reserved trash page for drained slots.
- A fixed number of SLOTS (the decode batch dimension) keeps every
  compiled shape static. Admission = host-side: allocate pages from the
  free list, run a compiled PREFILL (dense-cache forward over the
  bucket-padded prompt, then scatter into the slot's pages), seed the
  slot's first token.
- Decoding runs in compiled CHUNKS: ONE program advances ALL active
  slots ``decode_chunk`` tokens via a ``lax.scan`` (per-slot positions,
  paged attention reads, trash-page-guarded writes). Chunked continuous
  batching bounds host↔device round-trips — mandatory through the axon
  tunnel where per-step dispatch costs 100s of ms.
- Between chunks the host scheduler drains finished slots (eos or token
  budget), frees their pages, and admits queued requests into the freed
  slots — mixed-length streams flow through without ever reshaping the
  compiled program.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, no_grad

__all__ = ["ContinuousBatchingEngine", "ServedRequest"]


@dataclass
class ServedRequest:
    request_id: int
    prompt: np.ndarray                 # [S] int
    max_new_tokens: int
    eos_token_id: int | None = None
    tokens: list = field(default_factory=list)   # generated ids
    finished: bool = False
    finish_reason: str | None = None   # "eos" | "length"


def _next_bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    return n        # longer than every bucket: its own (exact) signature


class ContinuousBatchingEngine:
    """Schedules mixed-length generation streams through one compiled
    decode program. Greedy or temperature sampling.

    model: any CausalLM Layer implementing ``forward(ids, caches=, pos=,
    tables=)`` + ``init_kv_cache`` — Llama, Qwen2 (incl. MoE), and GPT2
    all qualify. num_slots is the decode batch size; total pool memory =
    num_pages * page_size tokens of KV per layer."""

    def __init__(self, model, num_slots=4, page_size=16, num_pages=None,
                 max_len=512, decode_chunk=16, prompt_buckets=(32, 64, 128),
                 eos_token_id=None, greedy=True, temperature=1.0,
                 seed=0):
        self.model = model
        cfg = model.config
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pages_per_slot = -(-self.max_len // self.page_size)
        # +1: page 0 is the reserved trash page
        self.num_pages = int(num_pages) if num_pages is not None else \
            self.num_slots * self.pages_per_slot + 1
        self.decode_chunk = int(decode_chunk)
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.eos = -1 if eos_token_id is None else int(eos_token_id)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)

        dtype = next(iter(model.parameters()))._data.dtype
        # MHA models (e.g. GPT2) carry no kv-head/head-dim fields
        kvh = getattr(cfg, "num_key_value_heads",
                      cfg.num_attention_heads)
        d = getattr(cfg, "head_dim",
                    cfg.hidden_size // cfg.num_attention_heads)
        # per layer: (key_pages, value_pages) — flat list like dense caches
        self.pools = []
        for _ in range(cfg.num_hidden_layers):
            for _kv in range(2):
                self.pools.append(Tensor(jnp.zeros(
                    (kvh, self.num_pages, self.page_size, d), dtype)))

        self._free_pages = deque(range(1, self.num_pages))
        # host-side slot state
        B, MP = self.num_slots, self.pages_per_slot
        self.tables = np.zeros((B, MP), np.int32)
        self.ctx = np.zeros((B,), np.int32)
        self.active = np.zeros((B,), bool)
        self.last_tok = np.zeros((B,), np.int32)
        self.limits = np.zeros((B,), np.int32)    # ctx budget per slot
        self.slot_eos = np.full((B,), -1, np.int32)  # per-request eos
        self.slot_req: list[ServedRequest | None] = [None] * B
        self.slot_pages: list[list] = [[] for _ in range(B)]

        self.queue: deque[ServedRequest] = deque()
        self.completed: list[ServedRequest] = []
        self._next_id = 0
        self._key = jax.random.PRNGKey(seed)
        self._prefill_fns = {}
        self._chunk_fn = None

    # ---- public API ------------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens,
                    eos_token_id=None) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_len {self.max_len}")
        # reject what the pool can NEVER satisfy — otherwise run() would
        # spin forever waiting for pages that cannot exist
        worst = max(self._bucket_for(prompt.size),
                    prompt.size + int(max_new_tokens))
        if -(-worst // self.page_size) > self.num_pages - 1:
            raise ValueError(
                f"request needs {-(-worst // self.page_size)} pages but "
                f"the pool only has {self.num_pages - 1} allocatable")
        req = ServedRequest(self._next_id, prompt, int(max_new_tokens),
                            eos_token_id if eos_token_id is not None
                            else (self.eos if self.eos >= 0 else None))
        self._next_id += 1
        self.queue.append(req)
        return req.request_id

    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.active.any())

    def step(self):
        """Admit what fits, decode one chunk, drain finished slots.
        Returns the requests completed by this step."""
        self._admit()
        if self.active.any():
            self._decode_chunk()
        return self._drain()

    def run(self):
        """Drive until every queued request completes; returns them in
        completion order."""
        done = []
        while self.has_work():
            n_before = len(done)
            done.extend(self.step())
            if (len(done) == n_before and not self.active.any()
                    and self.queue
                    and all(r is None for r in self.slot_req)):
                # nothing running, nothing finished, head request still
                # unadmittable — spinning would never terminate
                raise RuntimeError(
                    "serving engine stalled: queued request cannot be "
                    "admitted (page pool exhausted?)")
        return done

    # ---- admission / prefill --------------------------------------------

    def _bucket_for(self, prompt_len):
        """Padded prefill length: the smallest bucket covering the prompt,
        clamped to max_len, never below the prompt itself."""
        return min(max(_next_bucket(prompt_len, self.prompt_buckets),
                       prompt_len), self.max_len)

    def _alloc_pages(self, n):
        if len(self._free_pages) < n:
            return None
        return [self._free_pages.popleft() for _ in range(n)]

    def _admit(self):
        for slot in range(self.num_slots):
            if not self.queue:
                return
            if self.active[slot] or self.slot_req[slot] is not None:
                continue
            req = self.queue[0]
            bucket = self._bucket_for(len(req.prompt))
            need_tokens = max(bucket, len(req.prompt) + req.max_new_tokens)
            need = -(-need_tokens // self.page_size)
            pages = self._alloc_pages(need)
            if pages is None:
                return        # pool exhausted; retry after a drain
            self.queue.popleft()
            self.slot_pages[slot] = pages
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:len(pages)] = pages
            self.tables[slot] = row
            self._prefill(slot, req, bucket)

    def _prefill_fn(self, bucket):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        from ..jit import to_static
        model = self.model

        def prefill(ids, true_len_t, slot_tables, temperature, greedy,
                    key_t, *pools):
            """ids: [1, bucket]; returns (first_tok[1], new_pools...)."""
            with no_grad():
                dense = model.init_kv_cache(1, ids.shape[1])
                logits, dense = model(ids, caches=dense,
                                      pos=Tensor(jnp.zeros((), jnp.int32)))

            def fn(lg, tl, tbl, key, *leaves):
                from ..ops.paged_attention import pack_prompt_into_pages
                last = jax.lax.dynamic_index_in_dim(
                    lg[0], tl - 1, 0, False)          # [V]
                lgf = last.astype(jnp.float32)
                if greedy:
                    tok = jnp.argmax(lgf).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    tok = jax.random.categorical(
                        sub, lgf / temperature).astype(jnp.int32)
                n = len(leaves) // 2
                pool_l, dense_l = leaves[:n], leaves[n:]
                out = []
                for i in range(0, n, 2):   # pairs: (k pages, v pages)
                    kp, vp = pack_prompt_into_pages(
                        pool_l[i], pool_l[i + 1],
                        dense_l[i], dense_l[i + 1], tbl)
                    out.extend((kp, vp))
                return (tok.reshape(1), key) + tuple(out)

            res = _apply_multi(fn, [logits, true_len_t, slot_tables, key_t]
                               + list(pools) + list(dense),
                               n_out=2 + len(pools))
            return res

        fn = to_static(prefill)
        self._prefill_fns[bucket] = fn
        return fn

    def _prefill(self, slot, req, bucket):
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :len(req.prompt)] = req.prompt
        tl = len(req.prompt)
        fn = self._prefill_fn(bucket)
        res = fn(Tensor(jnp.asarray(ids)),
                 Tensor(jnp.asarray(tl, jnp.int32)),
                 Tensor(jnp.asarray(self.tables[slot])),
                 self.temperature, self.greedy, Tensor(self._key),
                 *self.pools)
        tok, key = res[0], res[1]
        self.pools = list(res[2:])
        self._key = key._data if isinstance(key, Tensor) else key
        first = int(np.asarray(tok._data)[0])
        req.tokens.append(first)
        self.slot_req[slot] = req
        self.last_tok[slot] = first
        self.ctx[slot] = tl
        self.slot_eos[slot] = -1 if req.eos_token_id is None \
            else int(req.eos_token_id)
        # ctx counts CACHE entries; one generated token is always pending
        # outside the cache, so the n-th token lands when ctx hits
        # tl + n - 1 (not tl + n)
        self.limits[slot] = tl + req.max_new_tokens - 1
        eos = req.eos_token_id
        if (eos is not None and first == eos) or req.max_new_tokens <= 1:
            # one-token request or instant eos: slot never becomes active
            self.active[slot] = False
            req.finished = True
            req.finish_reason = "eos" if (eos is not None and first == eos) \
                else "length"
        else:
            self.active[slot] = True

    # ---- chunked decode --------------------------------------------------

    def _chunk_static(self):
        if self._chunk_fn is not None:
            return self._chunk_fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature
        n_steps = self.decode_chunk

        def chunk(tok_t, ctx_t, act_t, lim_t, eos_t, tables_t, key_t,
                  *pools):
            fwd = model.forward

            def fn(tok, ctx, act, lim, eos_arr, tbl, key, *pool_leaves):
                b = tok.shape[0]

                def body(carry, _):
                    tok_c, ctx_c, act_c, key_c, leaves = carry
                    with no_grad():
                        logits, ncaches = fwd(
                            Tensor(tok_c.reshape(b, 1)),
                            caches=[Tensor(a) for a in leaves],
                            pos=Tensor(ctx_c[:, None]),
                            tables=(Tensor(tbl), Tensor(act_c)))
                    lg = logits[:, -1]._data.astype(jnp.float32)
                    if greedy:
                        nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                    else:
                        key_c, sub = jax.random.split(key_c)
                        nxt = jax.random.categorical(
                            sub, lg / temperature).astype(jnp.int32)
                    ctx_n = ctx_c + act_c.astype(jnp.int32)
                    nxt = jnp.where(act_c, nxt, tok_c)
                    # per-slot eos (a traced [B] array, -1 = none): each
                    # request may carry its own stop token
                    still = act_c & (ctx_n < lim) & \
                        ((eos_arr < 0) | (nxt != eos_arr))
                    new_leaves = tuple(t._data for t in ncaches)
                    out_tok = jnp.where(act_c, nxt, -1)
                    return (nxt, ctx_n, still, key_c, new_leaves), \
                        (out_tok, act_c)

                carry0 = (tok, ctx, act, key, tuple(pool_leaves))
                carry, (toks, emitted) = jax.lax.scan(
                    body, carry0, jnp.arange(n_steps))
                tok_f, ctx_f, act_f, key_f, leaves_f = carry
                return (toks.T, emitted.T, tok_f, ctx_f, act_f, key_f) \
                    + tuple(leaves_f)

            return _apply_multi(
                fn, [tok_t, ctx_t, act_t, lim_t, eos_t, tables_t, key_t]
                + list(pools), n_out=6 + len(pools))

        self._chunk_fn = to_static(chunk)
        return self._chunk_fn

    def _decode_chunk(self):
        fn = self._chunk_static()
        res = fn(Tensor(jnp.asarray(self.last_tok)),
                 Tensor(jnp.asarray(self.ctx)),
                 Tensor(jnp.asarray(self.active)),
                 Tensor(jnp.asarray(self.limits)),
                 Tensor(jnp.asarray(self.slot_eos)),
                 Tensor(jnp.asarray(self.tables)),
                 Tensor(self._key), *self.pools)
        toks, emitted, tok_f, ctx_f, act_f, key_f = res[:6]
        self.pools = list(res[6:])
        toks_np = np.asarray(toks._data)          # [B, n_steps]
        emitted_np = np.asarray(emitted._data)    # [B, n_steps] bool
        self.last_tok = np.asarray(tok_f._data).copy()
        self.ctx = np.asarray(ctx_f._data).copy()
        self.active = np.asarray(act_f._data).copy()
        self._key = key_f._data
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None or req.finished:
                continue
            for j in range(toks_np.shape[1]):
                if emitted_np[slot, j]:
                    req.tokens.append(int(toks_np[slot, j]))

    # ---- completion ------------------------------------------------------

    def _drain(self):
        done = []
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None:
                continue
            if not self.active[slot]:
                if not req.finished:
                    req.finished = True
                    eos = req.eos_token_id
                    req.finish_reason = "eos" if (
                        eos is not None and req.tokens
                        and req.tokens[-1] == eos) else "length"
                self._free_pages.extend(self.slot_pages[slot])
                self.slot_pages[slot] = []
                self.slot_req[slot] = None
                self.tables[slot] = 0
                self.ctx[slot] = 0
                self.limits[slot] = 0
                self.slot_eos[slot] = -1
                self.completed.append(req)
                done.append(req)
        return done


def _apply_multi(fn, tensors, n_out):
    """apply() with a tuple return of n_out arrays."""
    from ..framework.core import apply
    return apply(fn, *tensors, n_outputs=n_out, differentiable=False,
                 name="serving_engine")
