"""Continuous-batching LLM serving engine over paged KV caches.

Reference role: the serving layer PaddleNLP/FastDeploy put on top of
Paddle Inference (dynamic batching + paged/ragged KV attention for mixed-
length streams; reference mount empty, no cites — SURVEY.md §2.1
inference row, PAPERS.md ragged-paged-attention).

TPU-native design — the vLLM recipe restructured for XLA's static-shape
world:

- The KV cache is a global PAGE POOL per layer ([KVH, num_pages,
  page_size, D]); each admitted request owns a page list (its block
  table row). Page 0 is a reserved trash page for drained slots.
- A fixed number of SLOTS (the decode batch dimension) keeps every
  compiled shape static. Admission = host-side: allocate pages from the
  free list, run a compiled PREFILL (dense-cache forward over the
  bucket-padded prompt, then scatter into the slot's pages), seed the
  slot's first token.
- Decoding runs in compiled CHUNKS: ONE program advances ALL active
  slots ``decode_chunk`` tokens via a ``lax.scan`` (per-slot positions,
  paged attention reads, trash-page-guarded writes). Chunked continuous
  batching bounds host↔device round-trips — mandatory through the axon
  tunnel where per-step dispatch costs 100s of ms.
- Between chunks the host scheduler drains finished slots (eos or token
  budget), frees their pages, and admits queued requests into the freed
  slots — mixed-length streams flow through without ever reshaping the
  compiled program.
- Hot state (last token / context length / active mask / RNG key / page
  pools) is DEVICE-RESIDENT between chunks: each chunk call uploads one
  packed int32 array (tables+limits+eos) and fetches one packed int32
  array (emitted tokens + first-token echoes + ctx/active mirrors), and
  prefill never fetches — its first token lands in device state and is
  echoed through the next chunk's packed fetch. Measured on the tunnel
  (v5e): per-call overhead was ~0.5s with per-array
  uploads + a blocking scalar fetch per admission; the chunk's marginal
  per-token cost is identical to the fused dense decode (4.2 ms/step at
  batch 8 on the 1B config), so round-trips, not kernels, set the
  serving throughput.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, no_grad

__all__ = ["ContinuousBatchingEngine", "ServedRequest"]


@dataclass
class ServedRequest:
    request_id: int
    prompt: np.ndarray                 # [S] int
    max_new_tokens: int
    eos_token_id: int | None = None
    tokens: list = field(default_factory=list)   # generated ids
    finished: bool = False
    finish_reason: str | None = None   # "eos" | "length"


def _next_bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    return n        # longer than every bucket: its own (exact) signature


class ContinuousBatchingEngine:
    """Schedules mixed-length generation streams through one compiled
    decode program. Greedy or temperature sampling.

    model: any CausalLM Layer implementing ``forward(ids, caches=, pos=,
    tables=)`` + ``init_kv_cache`` — Llama, Qwen2 (incl. MoE), and GPT2
    all qualify. num_slots is the decode batch size; total pool memory =
    num_pages * page_size tokens of KV per layer."""

    def __init__(self, model, num_slots=4, page_size=16, num_pages=None,
                 max_len=512, decode_chunk=16, prompt_buckets=(32, 64, 128),
                 eos_token_id=None, greedy=True, temperature=1.0,
                 seed=0):
        self.model = model
        cfg = model.config
        self.cfg = cfg
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pages_per_slot = -(-self.max_len // self.page_size)
        # +1: page 0 is the reserved trash page
        self.num_pages = int(num_pages) if num_pages is not None else \
            self.num_slots * self.pages_per_slot + 1
        self.decode_chunk = int(decode_chunk)
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.eos = -1 if eos_token_id is None else int(eos_token_id)
        self.greedy = bool(greedy)
        self.temperature = float(temperature)

        dtype = next(iter(model.parameters()))._data.dtype
        # MHA models (e.g. GPT2) carry no kv-head/head-dim fields
        kvh = getattr(cfg, "num_key_value_heads",
                      cfg.num_attention_heads)
        d = getattr(cfg, "head_dim",
                    cfg.hidden_size // cfg.num_attention_heads)
        # per layer: (key_pages, value_pages) — flat list like dense caches
        self.pools = []
        for _ in range(cfg.num_hidden_layers):
            for _kv in range(2):
                self.pools.append(Tensor(jnp.zeros(
                    (kvh, self.num_pages, self.page_size, d), dtype)))

        self._free_pages = deque(range(1, self.num_pages))
        # host-side slot bookkeeping (admission decisions, drain)
        B, MP = self.num_slots, self.pages_per_slot
        self.tables = np.zeros((B, MP), np.int32)
        self.ctx = np.zeros((B,), np.int32)       # mirror (packed fetch)
        self.active = np.zeros((B,), bool)        # mirror (packed fetch)
        self.limits = np.zeros((B,), np.int32)    # ctx budget per slot
        self.slot_eos = np.full((B,), -1, np.int32)  # per-request eos
        self.slot_req: list[ServedRequest | None] = [None] * B
        self.slot_pages: list[list] = [[] for _ in range(B)]
        # pending first-token echo: slots admitted since the last chunk
        # whose prefill token has not been appended host-side yet
        self._pending_first = np.zeros((B,), bool)
        # echo snapshotted into a dispatched-but-unharvested chunk: the
        # slot must not drain until that harvest appends the token (a
        # one-shot request admitted mid-stream would otherwise finish
        # empty — its pending flag is cleared at dispatch, but the token
        # only arrives with the chunk's packed fetch)
        self._echo_inflight = np.zeros((B,), bool)

        # device-resident hot state (never round-trips between chunks);
        # admission mutates it with tiny async .at[slot].set dispatches
        self._dev_tok = jnp.zeros((B,), jnp.int32)
        self._dev_ctx = jnp.zeros((B,), jnp.int32)
        self._dev_act = jnp.zeros((B,), bool)
        self._dev_tbl = jnp.zeros((B, MP), jnp.int32)
        self._dev_lim = jnp.zeros((B,), jnp.int32)
        self._dev_eos = jnp.full((B,), -1, jnp.int32)

        self.queue: deque[ServedRequest] = deque()
        self.completed: list[ServedRequest] = []
        self._next_id = 0
        self._key = jax.random.PRNGKey(seed)
        self._prefill_fns = {}
        self._chunk_fn = None

        # perf observability (profiler subsystem): raw counters behind
        # the :meth:`gauges` surface — slot occupancy, admission/prefill
        # overlap, tok/s. Maintained unconditionally (integer adds);
        # mirrored into the trace layer only when tracing is enabled.
        self._stats = {"chunks": 0, "chunk_slot_steps": 0,
                       "active_slot_steps": 0, "tokens_emitted": 0,
                       "prefills": 0, "prefills_overlapped": 0,
                       "requests_completed": 0, "run_seconds": 0.0}
        self._overlap_admission = False

    # ---- public API ------------------------------------------------------

    def add_request(self, prompt_ids, max_new_tokens,
                    eos_token_id=None) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds engine max_len {self.max_len}")
        # reject what the pool can NEVER satisfy — otherwise run() would
        # spin forever waiting for pages that cannot exist
        worst = max(self._bucket_for(prompt.size),
                    prompt.size + int(max_new_tokens))
        if -(-worst // self.page_size) > self.num_pages - 1:
            raise ValueError(
                f"request needs {-(-worst // self.page_size)} pages but "
                f"the pool only has {self.num_pages - 1} allocatable")
        req = ServedRequest(self._next_id, prompt, int(max_new_tokens),
                            eos_token_id if eos_token_id is not None
                            else (self.eos if self.eos >= 0 else None))
        self._next_id += 1
        self.queue.append(req)
        return req.request_id

    def has_work(self) -> bool:
        return bool(self.queue) or bool(self.active.any())

    def step(self):
        """Admit what fits, decode one chunk, drain finished slots.
        Returns the requests completed by this step."""
        self._admit()
        if self.active.any():
            self._decode_chunk()
        return self._drain()

    def run(self):
        """Drive until every queued request completes; returns them in
        completion order.

        Pipelined: the NEXT chunk is ALWAYS dispatched before the
        previous chunk's packed output is fetched — device state chains
        asynchronously, so the harvest round-trip AND the whole
        admission wave (prefill programs, slot-state updates) execute
        while the speculative successor decodes on device: a prefill
        consumes the successor's output pools, so it simply joins the
        device stream after it, and the admitted slot starts decoding
        in the chunk after that. A slot that finished inside the
        previous chunk is inactive in the speculative successor (its
        device active flag is already False), so the overlap never
        decodes garbage; the admitted-into slots idle for exactly one
        in-flight chunk — measured cheaper than serializing admission
        on the tunnel round-trip (round-4 breakdown, BASELINE.md).
        Cost accepted (advisor round 4): when every slot finished
        inside the in-flight chunk and the queue is empty, one wasted
        chunk program is dispatched per drain wave."""
        import time as _time
        done = []
        inflight = None
        t_run0 = _time.perf_counter()
        try:
            while True:
                if inflight is not None:
                    # speculative successor first: device never idles
                    # while the host harvests, drains, and admits
                    nxt = self._dispatch_chunk() if self.active.any() \
                        else None
                    self._harvest_chunk(inflight)
                    done.extend(self._drain())
                    # prefills overlap nxt's on-device run — the gauge
                    # distinguishing overlapped from serialized admission
                    self._overlap_admission = nxt is not None
                    try:
                        self._admit()
                    finally:
                        self._overlap_admission = False
                    inflight = nxt
                    continue
                n_before = len(done)
                self._admit()
                done.extend(self._drain())
                if self.active.any():
                    inflight = self._dispatch_chunk()
                    continue
                if not self.queue:
                    break
                if (len(done) == n_before
                        and all(r is None for r in self.slot_req)):
                    # nothing running, nothing finished, head request
                    # still unadmittable — spinning never terminates
                    raise RuntimeError(
                        "serving engine stalled: queued request cannot "
                        "be admitted (page pool exhausted?)")
        finally:
            self._stats["run_seconds"] += _time.perf_counter() - t_run0
            self._emit_gauges()
        return done

    def gauges(self) -> dict:
        """Serving observability surface (profiler subsystem):

        - ``slot_occupancy``: emitted tokens / (chunks x slots x
          decode_chunk) — the fraction of compiled slot-steps that
          produced a token (the ~0.71 in BASELINE.md's CB ceiling).
        - ``active_occupancy``: slots active at dispatch / all slots —
          the drain/re-admit idle share specifically.
        - ``prefill_overlap_frac``: prefills dispatched while a decode
          chunk was in flight (the round-5 admission-overlap claim,
          now measured instead of asserted).
        - ``tokens_per_s``: emitted tokens / wall seconds inside run().
        """
        s = self._stats
        steps = s["chunk_slot_steps"]
        return {
            "slot_occupancy": s["tokens_emitted"] / steps if steps
            else 0.0,
            "active_occupancy": s["active_slot_steps"] / steps if steps
            else 0.0,
            "prefill_overlap_frac": (s["prefills_overlapped"]
                                     / s["prefills"]) if s["prefills"]
            else 0.0,
            "tokens_per_s": (s["tokens_emitted"] / s["run_seconds"])
            if s["run_seconds"] else 0.0,
            "chunks_dispatched": s["chunks"],
            "tokens_emitted": s["tokens_emitted"],
            "prefills": s["prefills"],
            "requests_completed": s["requests_completed"],
        }

    def reset_gauges(self):
        """Zero the gauge counters (e.g. after a warmup run whose lazy
        compiles would otherwise pollute tokens_per_s)."""
        for k in self._stats:
            self._stats[k] = 0.0 if k == "run_seconds" else 0

    def _emit_gauges(self):
        from ..profiler.trace import get_tracer
        tr = get_tracer()
        if not tr.enabled:
            return
        for name, val in self.gauges().items():
            tr.counter(f"serving/{name}",
                       round(val, 6) if isinstance(val, float) else val)

    # ---- admission / prefill --------------------------------------------

    def _bucket_for(self, prompt_len):
        """Padded prefill length: the smallest bucket covering the prompt,
        clamped to max_len, never below the prompt itself."""
        return min(max(_next_bucket(prompt_len, self.prompt_buckets),
                       prompt_len), self.max_len)

    def _alloc_pages(self, n):
        if len(self._free_pages) < n:
            return None
        return [self._free_pages.popleft() for _ in range(n)]

    def _admit(self):
        for slot in range(self.num_slots):
            if not self.queue:
                return
            if self.active[slot] or self.slot_req[slot] is not None:
                continue
            req = self.queue[0]
            bucket = self._bucket_for(len(req.prompt))
            need_tokens = max(bucket, len(req.prompt) + req.max_new_tokens)
            need = -(-need_tokens // self.page_size)
            pages = self._alloc_pages(need)
            if pages is None:
                return        # pool exhausted; retry after a drain
            self.queue.popleft()
            self.slot_pages[slot] = pages
            row = np.zeros((self.pages_per_slot,), np.int32)
            row[:len(pages)] = pages
            self.tables[slot] = row
            self._dev_tbl = self._dev_tbl.at[slot].set(
                jnp.asarray(row))
            self._prefill(slot, req, bucket)

    def _prefill_fn(self, bucket):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        from ..jit import to_static
        model = self.model

        def prefill(ids, true_len_t, slot_tables, temperature, greedy,
                    key_t, *pools):
            """ids: [1, bucket]; returns (first_tok[1], new_pools...)."""
            with no_grad():
                dense = model.init_kv_cache(1, ids.shape[1])
                logits, dense = model(ids, caches=dense,
                                      pos=Tensor(jnp.zeros((), jnp.int32)))

            def fn(lg, tl, tbl, key, *leaves):
                from ..ops.paged_attention import pack_prompt_into_pages
                last = jax.lax.dynamic_index_in_dim(
                    lg[0], tl - 1, 0, False)          # [V]
                lgf = last.astype(jnp.float32)
                if greedy:
                    tok = jnp.argmax(lgf).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    tok = jax.random.categorical(
                        sub, lgf / temperature).astype(jnp.int32)
                n = len(leaves) // 2
                pool_l, dense_l = leaves[:n], leaves[n:]
                out = []
                for i in range(0, n, 2):   # pairs: (k pages, v pages)
                    kp, vp = pack_prompt_into_pages(
                        pool_l[i], pool_l[i + 1],
                        dense_l[i], dense_l[i + 1], tbl)
                    out.extend((kp, vp))
                return (tok.reshape(1), key) + tuple(out)

            res = _apply_multi(fn, [logits, true_len_t, slot_tables, key_t]
                               + list(pools) + list(dense),
                               n_out=2 + len(pools))
            return res

        fn = to_static(prefill)
        self._prefill_fns[bucket] = fn
        return fn

    def _prefill(self, slot, req, bucket):
        self._stats["prefills"] += 1
        if self._overlap_admission:
            self._stats["prefills_overlapped"] += 1
        from ..profiler.trace import get_tracer
        _tr = get_tracer()
        if _tr.enabled:
            _tr.instant("serving/prefill", slot=slot, bucket=bucket,
                        overlapped=self._overlap_admission)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :len(req.prompt)] = req.prompt
        tl = len(req.prompt)
        fn = self._prefill_fn(bucket)
        res = fn(Tensor(jnp.asarray(ids)),
                 Tensor(jnp.asarray(tl, jnp.int32)),
                 Tensor(jnp.asarray(self.tables[slot])),
                 self.temperature, self.greedy, Tensor(self._key),
                 *self.pools)
        tok, key = res[0], res[1]
        self.pools = list(res[2:])
        self._key = key._data if isinstance(key, Tensor) else key
        # NO host fetch here: the first token stays on device and is
        # echoed back through the next chunk's packed fetch (a blocking
        # scalar read per admission would serialize the whole admission
        # wave on tunnel round-trips)
        tok_dev = tok._data if isinstance(tok, Tensor) else tok
        self._dev_tok = self._dev_tok.at[slot].set(tok_dev[0])
        self._dev_ctx = self._dev_ctx.at[slot].set(tl)
        self.slot_req[slot] = req
        self._pending_first[slot] = True
        self.ctx[slot] = tl
        self.slot_eos[slot] = -1 if req.eos_token_id is None \
            else int(req.eos_token_id)
        # ctx counts CACHE entries; one generated token is always pending
        # outside the cache, so the n-th token lands when ctx hits
        # tl + n - 1 (not tl + n)
        self.limits[slot] = tl + req.max_new_tokens - 1
        self._dev_lim = self._dev_lim.at[slot].set(int(self.limits[slot]))
        self._dev_eos = self._dev_eos.at[slot].set(
            int(self.slot_eos[slot]))
        one_shot = req.max_new_tokens <= 1
        # instant-eos (first token == stop token) is detected ON DEVICE
        # at the next chunk's entry; only the structural one-token case
        # is known host-side now
        self._dev_act = self._dev_act.at[slot].set(not one_shot)
        self.active[slot] = not one_shot

    # ---- chunked decode --------------------------------------------------

    def _chunk_static(self):
        if self._chunk_fn is not None:
            return self._chunk_fn
        from ..jit import to_static
        model = self.model
        greedy = self.greedy
        temperature = self.temperature
        n_steps = self.decode_chunk
        MP = self.pages_per_slot

        def chunk(tok_t, ctx_t, act_t, tbl_t, lim_t, eos_t, key_t,
                  *pools):
            fwd = model.forward

            def fn(tok, ctx, act, tbl, lim, eos_arr, key, *pool_leaves):
                b = tok.shape[0]
                # a freshly admitted slot whose prefill token already hit
                # its stop token must not decode (the host never saw the
                # token — instant-eos is detected here, on device)
                act = act & ((eos_arr < 0) | (tok != eos_arr))
                init_tok = tok

                def body(carry, _):
                    tok_c, ctx_c, act_c, key_c, leaves = carry
                    with no_grad():
                        logits, ncaches = fwd(
                            Tensor(tok_c.reshape(b, 1)),
                            caches=[Tensor(a) for a in leaves],
                            pos=Tensor(ctx_c[:, None]),
                            tables=(Tensor(tbl), Tensor(act_c)))
                    lg = logits[:, -1]._data.astype(jnp.float32)
                    if greedy:
                        nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                    else:
                        key_c, sub = jax.random.split(key_c)
                        nxt = jax.random.categorical(
                            sub, lg / temperature).astype(jnp.int32)
                    ctx_n = ctx_c + act_c.astype(jnp.int32)
                    nxt = jnp.where(act_c, nxt, tok_c)
                    # per-slot eos (a traced [B] array, -1 = none): each
                    # request may carry its own stop token
                    still = act_c & (ctx_n < lim) & \
                        ((eos_arr < 0) | (nxt != eos_arr))
                    new_leaves = tuple(t._data for t in ncaches)
                    out_tok = jnp.where(act_c, nxt, -1)
                    return (nxt, ctx_n, still, key_c, new_leaves), \
                        (out_tok, act_c)

                carry0 = (tok, ctx, act, key, tuple(pool_leaves))
                carry, (toks, emitted) = jax.lax.scan(
                    body, carry0, jnp.arange(n_steps))
                tok_f, ctx_f, act_f, key_f, leaves_f = carry
                # ONE packed int32 fetch carries everything the host
                # scheduler needs: emitted tokens, emission mask, the
                # first-token echo for freshly admitted slots, and the
                # ctx/active mirrors
                packed_out = jnp.concatenate(
                    [toks.T.astype(jnp.int32),
                     emitted.T.astype(jnp.int32),
                     init_tok[:, None].astype(jnp.int32),
                     ctx_f[:, None].astype(jnp.int32),
                     act_f[:, None].astype(jnp.int32)], axis=1)
                return (packed_out, tok_f, ctx_f, act_f, key_f) \
                    + tuple(leaves_f)

            return _apply_multi(fn, [tok_t, ctx_t, act_t, tbl_t, lim_t,
                                     eos_t, key_t]
                                + list(pools), n_out=5 + len(pools))

        self._chunk_fn = to_static(chunk)
        return self._chunk_fn

    def _dispatch_chunk(self):
        """Launch one chunk program (async) and chain the device state.
        Returns an in-flight record for :meth:`_harvest_chunk` — the
        packed output is NOT fetched here, so a caller may overlap the
        fetch with the next chunk's on-device compute."""
        fn = self._chunk_static()
        self._stats["chunks"] += 1
        self._stats["chunk_slot_steps"] += self.num_slots \
            * self.decode_chunk
        n_active = int(self.active.sum())
        self._stats["active_slot_steps"] += n_active * self.decode_chunk
        from ..profiler.trace import get_tracer
        _tr = get_tracer()
        if _tr.enabled:
            _tr.counter("serving/active_slots", n_active,
                        queued=len(self.queue))
        res = fn(Tensor(self._dev_tok), Tensor(self._dev_ctx),
                 Tensor(self._dev_act), Tensor(self._dev_tbl),
                 Tensor(self._dev_lim), Tensor(self._dev_eos),
                 Tensor(self._key), *self.pools)
        packed, tok_f, ctx_f, act_f, key_f = res[:5]
        self.pools = list(res[5:])
        self._dev_tok = tok_f._data
        self._dev_ctx = ctx_f._data
        self._dev_act = act_f._data
        self._key = key_f._data
        # snapshot the slot->request mapping and the pending-first mask:
        # by harvest time a drained slot may have been re-admitted to a
        # NEW request whose tokens belong to a later chunk
        rec = (packed, list(self.slot_req), self._pending_first.copy())
        self._echo_inflight |= self._pending_first
        self._pending_first[:] = False
        return rec

    def _harvest_chunk(self, rec):
        """Fetch one in-flight chunk's packed output and apply it."""
        packed, snap_req, pending = rec
        arr = np.asarray(packed._data)            # the ONE fetch
        n = self.decode_chunk
        toks_np = arr[:, :n]
        emitted_np = arr[:, n:2 * n].astype(bool)
        init_tok = arr[:, 2 * n]
        ctx_m = arr[:, 2 * n + 1].astype(np.int32)
        act_m = arr[:, 2 * n + 2].astype(bool)
        for slot in range(self.num_slots):
            if pending[slot]:
                # this harvest delivers the slot's first-token echo;
                # _drain may finish the slot again from here on
                self._echo_inflight[slot] = False
            req = snap_req[slot]
            if req is not self.slot_req[slot]:
                continue      # slot re-admitted since this dispatch
            self.ctx[slot] = ctx_m[slot]
            self.active[slot] = act_m[slot]
            if req is None:
                continue
            if pending[slot]:
                req.tokens.append(int(init_tok[slot]))
                self._stats["tokens_emitted"] += 1
            if req.finished:
                continue
            for j in range(n):
                if emitted_np[slot, j]:
                    req.tokens.append(int(toks_np[slot, j]))
                    self._stats["tokens_emitted"] += 1

    def _decode_chunk(self):
        self._harvest_chunk(self._dispatch_chunk())

    # ---- completion ------------------------------------------------------

    def _drain(self):
        done = []
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None:
                continue
            if self._echo_inflight[slot]:
                # first-token echo rides a dispatched-but-unharvested
                # chunk: finishing now would lose it (defer one loop)
                continue
            if not self.active[slot]:
                if self._pending_first[slot]:
                    # finished without any chunk running after admission
                    # (one-token request at the tail of the workload):
                    # the first token never got echoed — fetch it now
                    req.tokens.append(int(np.asarray(
                        self._dev_tok[slot])))
                    self._stats["tokens_emitted"] += 1
                    self._pending_first[slot] = False
                if not req.finished:
                    req.finished = True
                    eos = req.eos_token_id
                    req.finish_reason = "eos" if (
                        eos is not None and req.tokens
                        and req.tokens[-1] == eos) else "length"
                self._free_pages.extend(self.slot_pages[slot])
                self.slot_pages[slot] = []
                self.slot_req[slot] = None
                self.tables[slot] = 0
                self.ctx[slot] = 0
                self.limits[slot] = 0
                self.slot_eos[slot] = -1
                self.completed.append(req)
                self._stats["requests_completed"] += 1
                done.append(req)
        return done


def _apply_multi(fn, tensors, n_out):
    """apply() with a tuple return of n_out arrays."""
    from ..framework.core import apply
    return apply(fn, *tensors, n_outputs=n_out, differentiable=False,
                 name="serving_engine")
