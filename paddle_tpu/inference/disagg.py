"""Disaggregated prefill/decode serving (ISSUE 17).

Long prompts stall a colocated decode batch: every scheduler turn a
replica spends streaming a 2k-token prompt is a turn its short-chat
occupants wait for their next token. The standard scale-out move — the
deployment shape of the Gemma-on-TPU serving comparison in PAPERS.md —
is to split the fleet by phase: **prefill replicas** do nothing but
prompt ingestion, **decode replicas** do nothing but token streaming,
and finished prompt-KV pages migrate between them.

This repo already had every primitive; this module only composes them:

- the ragged kernel's page-granular KV layout (PR 7) makes the handoff
  a per-page copy — :meth:`ContinuousBatchingEngine._migrate_out`
  serializes full prompt pages with per-pool crc32s, and
  :meth:`~.serving.ContinuousBatchingEngine.import_migration` seeds
  them into the destination's prefix-cache radix index (PR 12), so the
  decode replica attaches them exactly like a prefix-cache hit at full
  match length and re-prefills only the unseen suffix;
- greedy streams are therefore **token-identical** to the colocated
  engine by the same recompute-replay contract every failover path
  already leans on — and a lost or damaged transfer degrades to plain
  prompt replay, never a wrong stream;
- the crc-framed wire + shadow-salvage discipline (PR 16) gives the
  cross-process transfer its fault model: the payload rides
  ``take_migrations``/``kv_import``/``kv_release`` RPCs (chunked
  transparently past the frame cap), a prefill worker dying
  mid-transfer salvages to prompt replay off the parent shadow, a
  decode worker dying mid-decode salvages emitted tokens through the
  existing breaker/retry path;
- the router (PR 11/13) gains role awareness: new prompts land on
  prefill-capable replicas (decode replicas are ordinary engines, so
  they still absorb traffic when every prefill replica is gone —
  cross-role failover), migrations target the least-occupied
  decode-capable replica, the migration leg lands in hop timelines and
  the federated ``disagg/*`` metrics, and admission quotes TTFT off
  prefill queue depth while :meth:`DisaggServingFleet.predicted_itl_s`
  quotes ITL off decode occupancy.

Failure matrix (who salvages what — pinned by ``tests/test_disagg*``):

===========================  ==========================================
event                        recovery
===========================  ==========================================
prefill replica dies         parked + in-flight requests salvage to
mid-transfer                 prompt replay on a sibling (shadow /
                             ``salvage_unfinished`` — payload is lost,
                             correctness never depended on it)
decode replica dies          emitted tokens salvage through the
mid-decode                   breaker/retry path; replay re-prefills
                             prompt + tokens anywhere (cross-role)
import fails / no decode     ``disagg/migration_failures``; the fleet
candidate                    re-routes the request for plain replay
payload damaged (crc)        destination stops seeding at the bad
                             block, requeues; suffix re-prefills
source never acked           exported pages stay pinned (audit counts
                             them) until ``release_exported``; an
                             engine rebuild drops pins with the index
===========================  ==========================================
"""

from __future__ import annotations

import base64
import time

import numpy as np

from ..profiler import flight_recorder as _frec
from ..profiler import metrics as _pmetrics
from .fleet import ServingFleet
from .serving import ServedRequest, record_hop

__all__ = ["DisaggServingFleet", "kv_payload_to_wire",
           "kv_payload_from_wire", "kv_payload_nbytes"]

# fleet-side migration vocabulary (docs/observability.md table;
# tools/check_metric_names.py lints these literals)
_pmetrics.declare("disagg/migrations", "counter",
                  "prefill->decode KV migrations completed (payload "
                  "imported, source acked)")
_pmetrics.declare("disagg/migration_failures", "counter",
                  "migrations that could not land on a decode replica "
                  "(no candidate, import error, dead destination) — "
                  "the request re-routed for plain prompt replay")
_pmetrics.declare("disagg/migration_ms", "histogram",
                  "per completed migration: router pickup of the "
                  "exported payload -> destination import ack, ms "
                  "(bounded reservoir)")
_pmetrics.declare("disagg/kv_bytes_moved", "counter",
                  "KV page content bytes carried by completed "
                  "migrations (pre-encoding payload size)")
_pmetrics.declare("disagg/prefill_queue_depth", "gauge",
                  "requests queued across prefill-capable replicas — "
                  "the per-role depth TTFT quotes ride")
_pmetrics.declare("disagg/decode_queue_depth", "gauge",
                  "requests queued + running across decode-capable "
                  "replicas — the occupancy ITL quotes ride")


# ---- kv_transfer payload codec (the PR-16 wire carries JSON) ------------

def kv_payload_to_wire(payload):
    """Engine migration payload (numpy page content) -> JSON-safe
    ``kv_transfer`` form: page data base64-encoded per pool, tokens and
    checksums as plain ints, one shared ``shape`` (every page block of
    a pool has identical geometry). The per-page crc32s computed at
    export ride along and are re-verified at import — corruption
    between the two b64 codecs (or a buggy transport) is caught by
    checksum, not trusted.

    Quantized KV (ISSUE 20) ships NATIVELY — the int8 page codes and
    their f32 scale pages are b64-encoded as exported, no
    dequant→requant round trip — so pool geometry is heterogeneous:
    per-pool ``shapes``/``dtypes`` lists (from the first block) ride
    next to the legacy shared ``shape``/``dtype`` fields, and the
    engine's ``kv_quant`` mode passes through for the destination's
    geometry handshake."""
    out = {k: payload[k] for k in ("version", "rid", "eff_len",
                                   "page_size", "n_pools", "dtype")}
    if "kv_quant" in payload:
        out["kv_quant"] = payload["kv_quant"]
    shape = None
    shapes = dtypes = None
    blocks = []
    for blk in payload["blocks"]:
        if shape is None and blk["data"]:
            shape = [int(x) for x in np.asarray(blk["data"][0]).shape]
            shapes = [[int(x) for x in np.asarray(d).shape]
                      for d in blk["data"]]
            dtypes = [str(np.asarray(d).dtype) for d in blk["data"]]
        blocks.append({
            "tokens": [int(t) for t in blk["tokens"]],
            "data": [base64.b64encode(
                np.ascontiguousarray(d).tobytes()).decode("ascii")
                for d in blk["data"]],
            "crc": [int(c) for c in blk["crc"]],
        })
    out["shape"] = shape
    if shapes is not None:
        out["shapes"] = shapes
        out["dtypes"] = dtypes
    out["blocks"] = blocks
    return out


def kv_payload_from_wire(obj):
    """Inverse of :func:`kv_payload_to_wire`: rebuild the numpy-form
    payload ``import_migration`` consumes. Malformed input degrades to
    an empty block list (the request still replays from its prompt) —
    a damaged transfer must never raise past the import seam."""
    out = {k: obj.get(k) for k in ("version", "rid", "eff_len",
                                   "page_size", "n_pools", "dtype")}
    if "kv_quant" in obj:
        out["kv_quant"] = obj["kv_quant"]
    blocks = []
    try:
        # per-pool geometry when present (quantized payloads mix int8
        # data pools with f32 scales pools); legacy single-shape
        # payloads fall back to the shared fields
        if obj.get("shapes"):
            shapes = [tuple(int(x) for x in s) for s in obj["shapes"]]
            dts = [np.dtype(str(d)) for d in obj["dtypes"]]
        else:
            shapes = dts = None
            dt = np.dtype(str(obj.get("dtype")))
            shape = tuple(int(x) for x in obj.get("shape") or ())
        for blk in obj.get("blocks") or []:
            blocks.append({
                "tokens": np.asarray(blk["tokens"], np.int32),
                "data": [np.frombuffer(
                    base64.b64decode(s),
                    dts[i] if dts is not None else dt).reshape(
                        shapes[i] if shapes is not None else shape)
                    for i, s in enumerate(blk["data"])],
                "crc": [int(c) for c in blk["crc"]],
            })
    except Exception:  # noqa: BLE001 — damaged payload: plain replay
        blocks = []
    out["blocks"] = blocks
    return out


def kv_payload_nbytes(payload):
    """Raw KV content bytes in a numpy-form payload (the
    ``disagg/kv_bytes_moved`` accounting unit)."""
    return sum(int(np.asarray(d).nbytes)
               for blk in payload.get("blocks") or ()
               for d in blk["data"])


# ---- the role-aware fleet ----------------------------------------------

class DisaggServingFleet(ServingFleet):
    """A :class:`~.fleet.ServingFleet` whose replicas carry a role —
    ``prefill`` | ``decode`` | ``both`` — with the router, migration
    scheduler and per-role SLO quoting on top (module docstring).

    ``engine_factory`` is either a callable accepting a ``role=``
    keyword (in-process replicas) or a ProcReplica worker spec dict
    (``{"factory": ..., "kwargs": {...}}``) whose kwargs gain the role;
    every replica inherits its role across supervised rebuilds and
    worker respawns because the role is baked into its factory/spec.

    Routing: new admissions prefer prefill-capable replicas (role !=
    "decode"); decode replicas absorb admissions only when no prefill
    replica will — the cross-role failover path. Migration imports
    target the least-loaded decode-capable replica. Everything else —
    breakers, hedging, exactly-once delivery, salvage — is the base
    router, unchanged."""

    def __init__(self, engine_factory, num_prefill=1, num_decode=1,
                 **kw):
        #: replica id -> role; consulted by the router overrides
        self.roles: dict[int, str] = {}
        super().__init__(engine_factory, num_replicas=0, **kw)
        self._h_migration = self.metrics.histogram("disagg/migration_ms")
        for _ in range(int(num_prefill)):
            self.add_role_replica("prefill")
        for _ in range(int(num_decode)):
            self.add_role_replica("decode")

    # -- role plumbing -----------------------------------------------------

    def _role_factory(self, role):
        base = self._factory
        if isinstance(base, dict):          # ProcReplica worker spec
            kw = dict(base.get("kwargs", {}))
            kw["role"] = role
            out = dict(base)
            out["kwargs"] = kw
            return out
        return lambda: base(role=role)

    def add_role_replica(self, role):
        """Register one replica with ``role`` baked into its factory
        (no warmup — mirrors the base ctor's initial registration)."""
        rep = self._add_replica(self._role_factory(role))
        self.roles[rep.id] = role
        return rep.id

    def scale_up(self, engine_factory=None, warm=True, role="both"):
        """Base :meth:`~.fleet.ServingFleet.scale_up` (warm before
        weight), with the new replica's role recorded; an explicit
        ``engine_factory`` is used as-is and simply tagged."""
        rid = super().scale_up(
            engine_factory or self._role_factory(role), warm=warm)
        self.roles[rid] = role
        return rid

    def _warm(self, rep):
        """Role-aware warmup. A prefill-role engine PARKS any request
        that still needs tokens after its first — only the fleet's
        migration pump collects parked requests, so the base
        sacrificial request would never finish and the warm loop
        would spin to its step bound. One generated token exercises
        the compiled program (slot activation is data, not shape), so
        prefill replicas warm with ``max_new=1`` and complete locally.

        The sacrificial PROMPT is long (the widest prompt bucket the
        engine provisions): a prefill replica exists to absorb long
        prompts, and the base fleet's 4-token decode-shaped warm
        request would compile only the narrowest bucket — the first
        routed long prompt would then eat the wide bucket's XLA
        compile inside the serving path, exactly the latency warmup
        exists to take off it (ISSUE 19)."""
        if self._role(rep) != "prefill":
            return super()._warm(rep)
        eng = rep.engine
        buckets = getattr(eng, "prompt_buckets", None)
        plen = int(max(buckets)) if buckets \
            else 2 * int(getattr(eng, "page_size", 8))
        plen = max(4, min(plen, int(eng.max_len) - 2))
        wreq = ServedRequest(-1, np.zeros((plen,), np.int32), 1, None)
        wreq.t_arrive = time.perf_counter()
        eng.requeue(wreq)
        for _ in range(512):
            if not rep.has_work():
                break
            rep.step()
        eng.reset_gauges()

    def _role(self, rep):
        return self.roles.get(rep.id, "both")

    def _prefill_capable(self, rep):
        return self._role(rep) != "decode"

    def _decode_capable(self, rep):
        return self._role(rep) != "prefill"

    # -- role-aware routing ------------------------------------------------

    def _candidates(self, exclude=(), prefer=None):
        # base order (health, least-loaded, affinity, p99), then a
        # STABLE partition: prefill-capable replicas first. _assign
        # walks candidates in order, so decode replicas take new
        # admissions only when every prefill-capable replica is gone
        # or shedding — cross-role failover without a special path.
        reps = super()._candidates(exclude, prefer)
        reps.sort(key=lambda r: 0 if self._prefill_capable(r) else 1)
        return reps

    def _pick_decode(self, exclude=()):
        """Migration target: the least-occupied decode-capable ready
        replica (never the source)."""
        cands = [r for r in self.replicas.values()
                 if r.takes_weight() and r.id not in exclude
                 and self._decode_capable(r)]
        if not cands:
            return None
        return min(cands, key=lambda r: (r.load(), r.id))

    # -- migration scheduling ----------------------------------------------

    def step(self):
        done = super().step()
        self._pump_migrations()
        self._emit_role_gauges()
        return done

    def _pump_migrations(self):
        """Drain every prefill replica's exported (request, payload)
        pairs and land each on a decode replica: import (the payload
        becomes destination prefix-cache residents + a requeue), move
        the attempt's ownership, ack the source so its pinned pages
        become ordinary cache. Any failure re-routes the request for
        plain prompt replay through the base retry machinery — a
        migration can be lost, the request cannot."""
        for rep in list(self.replicas.values()):
            if not rep.live() or not self._prefill_capable(rep):
                continue
            try:
                migrations = rep.take_migrations()
            except (KeyboardInterrupt, SystemExit, AssertionError):
                raise
            except Exception:  # noqa: BLE001 — dead/hung source: its
                continue       # parked requests salvage via the shadow
            for req, payload in migrations:
                self._migrate_one(rep, req, payload)

    def _migrate_one(self, src, req, payload):
        t0 = time.perf_counter()
        tr = self._reqs.get(req.request_id)
        if tr is None or tr.done is not None or tr.cancelled:
            # decided/cancelled while parked: nothing to move — just
            # unpin the source (the reap owns the typed completion)
            self._release_quiet(src, req.request_id)
            if tr is not None and tr.cancelled and tr.done is None:
                tr.attempts.pop(src.id, None)
                tr.carry = req       # the pending reap completes it
            return
        dest = self._pick_decode(exclude=(src.id,))
        err = None
        if dest is not None:
            try:
                dest.import_migration(req, payload)
            except (KeyboardInterrupt, SystemExit, AssertionError):
                raise
            except Exception as exc:  # noqa: BLE001 — failed import
                err = exc              # degrades to prompt replay
        if dest is None or err is not None:
            self.metrics.counter("disagg/migration_failures").inc()
            record_hop(req, "migrate_failed",
                       src=src.id,
                       dest=dest.id if dest is not None else None,
                       error=repr(err)[:80] if err else "no candidate")
            _frec.record_event("disagg_migrate_failed", fid=tr.fid,
                               src=src.id, error=repr(err)[:120]
                               if err else "no candidate")
            self._release_quiet(src, req.request_id)
            # prompt replay on whatever replica admission picks next
            # turn — an infrastructure miss, not a request failure, so
            # no retry budget burns (the drain-eviction discipline).
            # no_migrate pins the replay colocated: without it a
            # decode-fleet outage would loop prefill -> park -> fail
            # forever instead of degrading to a colocated stream
            req.no_migrate = True
            tr.attempts.pop(src.id, None)
            tr.carry = req
            tr.not_before = time.perf_counter()
            self.metrics.counter("fleet/requeued").inc()
            return
        # success: ownership moves src -> dest, source unpins
        tr.attempts.pop(src.id, None)
        tr.attempts[dest.id] = req
        self._release_quiet(src, req.request_id)
        ms = (time.perf_counter() - t0) * 1e3
        moved = kv_payload_nbytes(payload)
        self.metrics.counter("disagg/migrations").inc()
        self.metrics.counter("disagg/kv_bytes_moved").inc(moved)
        self._h_migration.observe(ms)
        record_hop(req, "migrate", src=src.id, dest=dest.id,
                   pages=len(payload.get("blocks") or ()),
                   bytes=moved, ms=round(ms, 3))
        _frec.record_event("disagg_migrate", fid=tr.fid, src=src.id,
                           dest=dest.id, bytes=moved,
                           ms=round(ms, 3))

    @staticmethod
    def _release_quiet(src, request_id):
        try:
            src.release_exported(request_id)
        except (KeyboardInterrupt, SystemExit, AssertionError):
            raise
        except Exception:  # noqa: BLE001 — a dead source has no pins
            pass           # left to release (its index died with it)

    # -- per-role SLO quoting ----------------------------------------------

    def prefill_queue_depth(self):
        """Requests waiting across prefill-capable replicas — the
        depth new-admission TTFT quotes ride (admission controllers on
        prefill replicas already fold their own queue drain into
        :meth:`~.reliability.AdmissionController.predicted_ttft_s`;
        this is the fleet-level gauge of the same signal)."""
        return sum(len(r.engine.queue) for r in self.replicas.values()
                   if r.live() and self._prefill_capable(r))

    def decode_queue_depth(self):
        """Queued + running requests across decode-capable replicas."""
        n = 0
        for r in self.replicas.values():
            if not r.live() or not self._decode_capable(r):
                continue
            n += len(r.engine.queue)
            n += sum(1 for q in r.engine.slot_req
                     if q is not None and not q.finished)
        return n

    def predicted_ttft_s(self):
        """Fleet TTFT quote for a request submitted NOW: the best
        prefill-capable replica's admission prediction (their
        controllers read prefill queue depth by construction — new
        prompts only land there). None while no history exists."""
        preds = []
        for r in self.replicas.values():
            if r.takes_weight() and self._prefill_capable(r):
                p = r.admission.predicted_ttft_s()
                if p is not None:
                    preds.append(p)
        return min(preds) if preds else None

    def predicted_itl_s(self):
        """Fleet ITL quote: the best decode-capable replica's observed
        itl p50, scaled by decode occupancy (a full decode pool shares
        scheduler turns across more streams). None while cold."""
        p50s, slots, busy = [], 0, 0
        for r in self.replicas.values():
            if not r.takes_weight() or not self._decode_capable(r):
                continue
            h = r.engine.metrics.get("serving/itl_ms")
            if h is not None and h.count:
                p50s.append(h.percentile(50) / 1e3)
            slots += max(1, r.engine.num_slots)
            busy += sum(1 for q in r.engine.slot_req
                        if q is not None and not q.finished)
        if not p50s:
            return None
        occupancy = busy / max(1, slots)
        return min(p50s) * (1.0 + occupancy)

    def _emit_role_gauges(self):
        self.metrics.gauge("disagg/prefill_queue_depth").set(
            self.prefill_queue_depth())
        self.metrics.gauge("disagg/decode_queue_depth").set(
            self.decode_queue_depth())

    # -- observability -----------------------------------------------------

    def gauges(self) -> dict:
        g = super().gauges()

        def c(name):
            return self.metrics.counter(name).value

        g.update({
            "roles": dict(self.roles),
            "migrations": c("disagg/migrations"),
            "migration_failures": c("disagg/migration_failures"),
            "kv_bytes_moved": c("disagg/kv_bytes_moved"),
            "migration_ms_p99": self._h_migration.percentile(99),
            "prefill_queue_depth": self.prefill_queue_depth(),
            "decode_queue_depth": self.decode_queue_depth(),
        })
        return g
