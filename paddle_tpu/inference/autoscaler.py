"""SLO-driven fleet autoscaler (ISSUE 19): the controller that closes
the loop over the serving stack.

The fleet has every actuator (:meth:`~.fleet.ServingFleet.scale_up` /
:meth:`~.fleet.ServingFleet.scale_down` / :meth:`~.fleet.ServingFleet.
eject`) and every sensor (the federated metrics plane, per-tenant SLO
burn rates, admission queue depth and shed rate, slot occupancy,
prefix-cache hit rate, per-role pressure on a
:class:`~.disagg.DisaggServingFleet`) — this module connects them.

**Control loop.** :meth:`FleetAutoscaler.tick` samples one signal
snapshot, evaluates the rule chain, and drives at most ONE actuator
call. Scale-ups are warm-spare: the base fleet's ``scale_up`` compiles
the new replica's programs on a sacrificial request before it takes
router weight, so a flash crowd never lands on a cold XLA cache.
Scale-downs are drain-based: ``scale_down`` stops admission
immediately and in-flight work finishes (or hands off through the
engine's ``handoff()`` hook) — the autoscaler never ejects.

**Rules** (first match fires):

- *scale up* when any pressure signal crosses its high-water mark:
  worst per-tenant SLO burn rate >= ``burn_high`` (the error budget is
  burning faster than it refills), observed shed rate > ``shed_high``,
  admission queue depth per ready replica >= ``queue_high``, or slot
  occupancy >= ``occupancy_high``.
- *scale down* when EVERY signal sits below its low-water mark
  (``queue_low`` / ``occupancy_low``, zero sheds, burn < 1) for
  ``down_stable_ticks`` consecutive ticks — one idle tick is noise,
  a stable idle plateau is capacity.
- otherwise *hold* — the deadband between the marks is where a
  well-provisioned fleet lives.

**Hysteresis.** Any applied action opens a quiet period
(``up_cooldown_s`` after a scale-up, ``down_cooldown_s`` after a
scale-down) during which EVERY further action is blocked — by
construction no up+down pair can land within one cooldown, the
flapping invariant the scenario gate asserts. Bounds
(``min_replicas`` / ``max_replicas``) and the chip budget are checked
after the rule fires; a wanted-but-blocked action is recorded as a
``blocked`` decision so the operator can see the controller straining
against its limits.

**Role awareness.** On a :class:`~.disagg.DisaggServingFleet` the
scale-up rule picks the role under pressure — ``prefill`` when the
prefill admission queue is deep, ``decode`` when the decode pool's
slots are saturated, ``both`` when both are hot — and scale-down never
drains the last prefill-capable or last decode-capable replica.

**Cost model.** ``chips_per_replica`` prices a replica;
``chip_seconds`` integrates ready-replica chip time across ticks (the
denominator of the bench's goodput-per-chip frontier), and an optional
``chip_budget`` caps the fleet's instantaneous chip footprint.

**Explainability.** Every evaluation produces a structured record —
signals in, rule fired, action out — kept in a bounded log, exposed as
the fleet's ``autoscaler`` /statusz section, and counted in the
``autoscale/*`` metrics (docs/observability.md table).
"""

from __future__ import annotations

import time
from collections import deque

from ..profiler import flight_recorder as _frec
from ..profiler import metrics as _pmetrics

__all__ = ["FleetAutoscaler"]

_pmetrics.declare("autoscale/ticks", "counter",
                  "autoscaler control-loop evaluations (one signal "
                  "snapshot + rule-chain pass each)")
_pmetrics.declare("autoscale/scale_ups", "counter",
                  "warm-spare scale_up actions the autoscaler applied "
                  "(role-tagged on a disagg fleet)")
_pmetrics.declare("autoscale/scale_downs", "counter",
                  "drain-based scale_down actions the autoscaler "
                  "applied")
_pmetrics.declare("autoscale/blocked", "counter",
                  "actions a rule wanted but hysteresis refused "
                  "(cooldown quiet period, min/max replica bounds, "
                  "chip budget)")
_pmetrics.declare("autoscale/decisions", "counter",
                  "non-hold decision records appended to the bounded "
                  "decision log (scale_ups + scale_downs + blocked)")
_pmetrics.declare("autoscale/chip_seconds", "counter",
                  "integral of ready-replica chip time across ticks "
                  "(chips_per_replica x ready replicas x seconds) — "
                  "the goodput-per-chip frontier denominator")
_pmetrics.declare("autoscale/slo_burn", "gauge",
                  "worst per-(rule, tenant) SLO burn rate in the "
                  "fleet tracker at the last tick (1.0 = burning the "
                  "error budget exactly as fast as it refills)")


class FleetAutoscaler:
    """The closed-loop controller over one :class:`~.fleet.
    ServingFleet` (or :class:`~.disagg.DisaggServingFleet`) — module
    docstring. Construction attaches the controller as
    ``fleet.autoscaler`` so the fleet's /statusz carries the decision
    log; the caller drives :meth:`tick` (the scenario harness does it
    once per harness tick).

    ``now_fn`` injects the clock for deterministic tests, mirroring
    :class:`~..profiler.slo.SLOTracker`."""

    def __init__(self, fleet, *, min_replicas=1, max_replicas=4,
                 chips_per_replica=1.0, chip_budget=None,
                 up_cooldown_s=2.0, down_cooldown_s=4.0,
                 queue_high=4.0, queue_low=0.5,
                 occupancy_high=0.85, occupancy_low=0.35,
                 burn_high=2.0, shed_high=0.0,
                 down_stable_ticks=3, max_decisions=256,
                 warm=True, now_fn=None):
        if max_replicas < min_replicas:
            raise ValueError("max_replicas < min_replicas")
        if queue_low >= queue_high or occupancy_low >= occupancy_high:
            raise ValueError("deadband inverted: the low-water mark "
                             "must sit strictly below the high one")
        self.fleet = fleet
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.chips_per_replica = float(chips_per_replica)
        self.chip_budget = None if chip_budget is None \
            else float(chip_budget)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.occupancy_high = float(occupancy_high)
        self.occupancy_low = float(occupancy_low)
        self.burn_high = float(burn_high)
        self.shed_high = float(shed_high)
        self.down_stable_ticks = int(down_stable_ticks)
        self.warm = bool(warm)
        self._now = now_fn or time.perf_counter
        self._tick = 0
        self._quiet_until = 0.0     # after ANY action: no action at
        self._quiet_kind = None     # all until this instant (flapping
        self._idle_ticks = 0        # invariant by construction)
        self._last_t = None
        self.decisions = deque(maxlen=int(max_decisions))
        m = fleet.metrics
        self._c_ticks = m.counter("autoscale/ticks")
        self._c_ups = m.counter("autoscale/scale_ups")
        self._c_downs = m.counter("autoscale/scale_downs")
        self._c_blocked = m.counter("autoscale/blocked")
        self._c_decisions = m.counter("autoscale/decisions")
        self._c_chip_s = m.counter("autoscale/chip_seconds")
        self._g_burn = m.gauge("autoscale/slo_burn")
        fleet.autoscaler = self

    # ---- signals ---------------------------------------------------------

    @property
    def _disagg(self):
        return getattr(self.fleet, "roles", None) is not None

    def _worst_burn(self):
        slo = getattr(self.fleet, "slo", None)
        if slo is None:
            return 0.0
        worst = 0.0
        for rule in slo.summary()["rules"].values():
            for lbl in rule["labels"].values():
                worst = max(worst, lbl["burn_rate"])
        return worst

    def signals(self) -> dict:
        """One snapshot of every pressure signal the rules read —
        embedded verbatim in the tick's decision record, so any
        decision reconstructs from its log entry alone."""
        fleet = self.fleet
        ready = [r for r in fleet.replicas.values()
                 if r.takes_weight()]
        queue = sum(r.queue_depth() for r in ready)
        shed = sum(r.shed_rate() for r in ready)
        slots = busy = 0
        hits = []
        for r in ready:
            eng = r.engine
            slots += max(1, int(getattr(eng, "num_slots", 1)))
            busy += sum(1 for q in eng.slot_req
                        if q is not None and not q.finished)
            try:
                hits.append(float(r.supervisor.gauges().get(
                    "prefix_cache_hit_rate", 0.0)))
            except Exception:  # noqa: BLE001 — a replica mid-teardown
                pass           # must not blind the whole snapshot
        sig = {
            "replicas": len(fleet.replicas),
            "ready": len(ready),
            "queue_depth": queue,
            "queue_per_replica": queue / max(1, len(ready)),
            "shed_rate": round(shed, 4),
            "slot_occupancy": busy / max(1, slots),
            "prefix_cache_hit_rate": round(
                sum(hits) / len(hits), 4) if hits else 0.0,
            "slo_burn": round(self._worst_burn(), 4),
        }
        if self._disagg:
            n_pre = [r for r in ready
                     if fleet._prefill_capable(r)]
            n_dec = [r for r in ready if fleet._decode_capable(r)]
            dec_slots = sum(max(1, r.engine.num_slots) for r in n_dec)
            dec_busy = sum(1 for r in n_dec for q in r.engine.slot_req
                           if q is not None and not q.finished)
            sig["prefill_queue_per_replica"] = (
                fleet.prefill_queue_depth() / max(1, len(n_pre)))
            sig["decode_occupancy"] = dec_busy / max(1, dec_slots)
            sig["prefill_ready"] = len(n_pre)
            sig["decode_ready"] = len(n_dec)
        return sig

    # ---- the rule chain --------------------------------------------------

    def _up_rule(self, sig):
        """First pressure signal over its high-water mark, or None.
        The capacity floor outranks every pressure signal: a fleet
        below ``min_replicas`` ready (an operator drain, an ejection)
        reads ZERO queue/occupancy/shed precisely because nothing can
        admit — pressure rules alone would never backfill it."""
        if sig["ready"] < self.min_replicas:
            return "below_min_replicas"
        if sig["slo_burn"] >= self.burn_high:
            return "slo_burn_high"
        if sig["shed_rate"] > self.shed_high:
            return "shed_rate_high"
        if sig["queue_per_replica"] >= self.queue_high:
            return "queue_depth_high"
        if sig["slot_occupancy"] >= self.occupancy_high:
            return "occupancy_high"
        return None

    def _idle(self, sig):
        return (sig["queue_per_replica"] <= self.queue_low
                and sig["slot_occupancy"] <= self.occupancy_low
                and sig["shed_rate"] <= 0.0
                and sig["slo_burn"] < 1.0)

    def _pick_role(self, sig):
        """Which role is under pressure on a disagg fleet: deep
        prefill admission queue -> ``prefill``, saturated decode slots
        -> ``decode``, both hot -> ``both``. A colocated fleet has no
        roles — returns None."""
        if not self._disagg:
            return None
        pre_hot = sig["prefill_queue_per_replica"] >= self.queue_high \
            or sig["prefill_ready"] == 0
        dec_hot = sig["decode_occupancy"] >= self.occupancy_high \
            or sig["decode_ready"] == 0
        if pre_hot and dec_hot:
            return "both"
        if dec_hot:
            return "decode"
        return "prefill"

    def _down_target(self):
        """The replica a drain should take: the least-loaded ready
        one, never the last prefill-capable or decode-capable replica
        of a disagg fleet (a role going dark is an outage, not a
        saving). None when no replica can be spared."""
        fleet = self.fleet
        ready = [r for r in fleet.replicas.values()
                 if r.state == "ready"]
        if len(ready) <= self.min_replicas:
            return None
        for rep in sorted(ready, key=lambda r: (r.load(), r.id)):
            if self._disagg:
                pre = [r for r in ready if fleet._prefill_capable(r)]
                dec = [r for r in ready if fleet._decode_capable(r)]
                if fleet._prefill_capable(rep) and len(pre) <= 1:
                    continue
                if fleet._decode_capable(rep) and len(dec) <= 1:
                    continue
            return rep
        return None

    # ---- the loop --------------------------------------------------------

    def tick(self) -> dict:
        """One control-loop evaluation; returns this tick's decision
        record (always — ``hold`` included), having applied at most
        one actuator call."""
        now = self._now()
        self._tick += 1
        self._c_ticks.inc()
        if self._last_t is not None:
            ready = sum(1 for r in self.fleet.replicas.values()
                        if r.takes_weight())
            self._c_chip_s.inc(max(0.0, now - self._last_t)
                               * ready * self.chips_per_replica)
        self._last_t = now
        sig = self.signals()
        self._g_burn.set(sig["slo_burn"])
        # keep the scrape surface (gauges the fleet normally refreshes
        # only at end-of-run) fresh while the controller drives step()
        emit = getattr(self.fleet, "_emit_gauges", None)
        if emit is not None:
            emit()

        rule = self._up_rule(sig)
        if rule is not None:
            self._idle_ticks = 0
            return self._act_up(rule, sig, now)
        if self._idle(sig):
            self._idle_ticks += 1
            if self._idle_ticks >= self.down_stable_ticks:
                return self._act_down("idle_stable", sig, now)
            return self._record("hold", "idle_warming", sig, now,
                                reason=f"idle {self._idle_ticks}/"
                                       f"{self.down_stable_ticks} "
                                       "ticks")
        self._idle_ticks = 0
        return self._record("hold", "deadband", sig, now,
                            reason="every signal inside the deadband")

    # ---- actions ---------------------------------------------------------

    def _act_up(self, rule, sig, now):
        if now < self._quiet_until:
            return self._blocked(rule, sig, now, "scale_up",
                                 f"cooldown ({self._quiet_kind}) for "
                                 f"{self._quiet_until - now:.3f}s more")
        live = sum(1 for r in self.fleet.replicas.values()
                   if r.live() or r.state == "warming")
        if live >= self.max_replicas:
            return self._blocked(rule, sig, now, "scale_up",
                                 f"at max_replicas={self.max_replicas}")
        if self.chip_budget is not None and \
                (live + 1) * self.chips_per_replica > self.chip_budget:
            return self._blocked(rule, sig, now, "scale_up",
                                 f"chip budget {self.chip_budget} "
                                 "would be exceeded")
        role = self._pick_role(sig)
        if role is not None:
            rid = self.fleet.scale_up(warm=self.warm, role=role)
        else:
            rid = self.fleet.scale_up(warm=self.warm)
        self._c_ups.inc()
        self._quiet_until = self._now() + self.up_cooldown_s
        self._quiet_kind = "scale_up"
        return self._record("scale_up", rule, sig, now, replica=rid,
                            role=role,
                            reason=f"{rule} -> warm spare"
                                   + (f" ({role})" if role else ""))

    def _act_down(self, rule, sig, now):
        if now < self._quiet_until:
            return self._blocked(rule, sig, now, "scale_down",
                                 f"cooldown ({self._quiet_kind}) for "
                                 f"{self._quiet_until - now:.3f}s more")
        rep = self._down_target()
        if rep is None:
            return self._blocked(rule, sig, now, "scale_down",
                                 f"at min_replicas={self.min_replicas}"
                                 " or last replica of a role")
        role = self.fleet.roles.get(rep.id) if self._disagg else None
        self.fleet.scale_down(replica_id=rep.id)
        self._c_downs.inc()
        self._idle_ticks = 0
        self._quiet_until = self._now() + self.down_cooldown_s
        self._quiet_kind = "scale_down"
        return self._record("scale_down", rule, sig, now,
                            replica=rep.id, role=role,
                            reason=f"{rule} -> drain least-loaded "
                                   f"replica {rep.id}")

    # ---- the decision log ------------------------------------------------

    def _blocked(self, rule, sig, now, wanted, why):
        self._c_blocked.inc()
        return self._record("blocked", rule, sig, now, wanted=wanted,
                            reason=why)

    def _record(self, action, rule, sig, now, *, replica=None,
                role=None, wanted=None, reason=""):
        rec = {"tick": self._tick, "t": round(now, 6),
               "action": action, "rule": rule, "reason": reason,
               "signals": sig}
        if replica is not None:
            rec["replica"] = replica
        if role is not None:
            rec["role"] = role
        if wanted is not None:
            rec["wanted"] = wanted
        self.decisions.append(rec)
        if action != "hold":
            self._c_decisions.inc()
            _frec.record_event("autoscale_" + action, rule=rule,
                               reason=reason)
        return rec

    @property
    def chip_seconds(self):
        """Accrued chip-seconds (the cost-model integral so far)."""
        return float(self._c_chip_s.value)

    def actions(self):
        """The applied-action subset of the log, oldest first — what
        the no-flapping assertion and the scenario gates read."""
        return [d for d in self.decisions
                if d["action"] in ("scale_up", "scale_down")]

    def statusz(self) -> dict:
        """The ``autoscaler`` /statusz section: config, cost model,
        counters, and the full bounded decision log (newest last) —
        every decision reconstructable from here."""
        return {
            "config": {
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "chips_per_replica": self.chips_per_replica,
                "chip_budget": self.chip_budget,
                "up_cooldown_s": self.up_cooldown_s,
                "down_cooldown_s": self.down_cooldown_s,
                "queue_high": self.queue_high,
                "queue_low": self.queue_low,
                "occupancy_high": self.occupancy_high,
                "occupancy_low": self.occupancy_low,
                "burn_high": self.burn_high,
                "shed_high": self.shed_high,
                "down_stable_ticks": self.down_stable_ticks,
            },
            "ticks": int(self._c_ticks.value),
            "scale_ups": int(self._c_ups.value),
            "scale_downs": int(self._c_downs.value),
            "blocked": int(self._c_blocked.value),
            "chip_seconds": round(self.chip_seconds, 4),
            "quiet_until": round(self._quiet_until, 6),
            "decisions": list(self.decisions),
        }
