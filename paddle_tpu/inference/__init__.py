"""``paddle.inference`` — the inference engine (Paddle Inference parity).

Reference: ``paddle/fluid/inference/`` AnalysisPredictor — load a saved
program, run analysis passes (op fusion, TRT subgraph capture, precision
rewrites), then execute with zero-copy input/output handles (SURVEY.md
§2.1 "Inference engine", §3.6; reference mount empty, no file:line cites).

TPU-native design — NOT a port:

- The saved model is ``paddle_tpu.jit.save`` output: a serialized
  ``jax.export`` artifact (``.pdexported`` — executable without the
  python class, the role ``.pdmodel`` ProgramDesc plays) plus the
  ``.pdiparams`` state dict and ``.pdmodel`` StableHLO text for
  inspection.
- Analysis passes ARE XLA: fusion, layout, constant folding and
  scheduling happen when the exported StableHLO is jit-compiled for the
  target chip. ``Config`` knobs that select reference passes
  (ir_optim, memory_optim) therefore turn into no-ops recorded for
  API compatibility; precision knobs map to a bf16 autocast wrapper.
- Zero-copy handles: ``Tensor.copy_from_cpu`` stages a device put,
  ``run()`` executes the compiled function, ``copy_to_cpu`` brings the
  result back. ``Predictor.clone()`` shares weights (the reference's
  multi-predictor Scope sharing) — jax.Arrays are immutable so sharing
  is free.
"""

from __future__ import annotations

import enum
import os

import numpy as np

import jax
import jax.numpy as jnp

from .reliability import (AdmissionController, DeadlineExceeded,
                          EngineSupervisor, Overloaded,
                          ReplicaFailed, RequestCancelled,
                          RequestQuarantined, ServingError)
from .serving import ContinuousBatchingEngine, ServedRequest
from .fleet import FleetReplica, ServingFleet
from .disagg import DisaggServingFleet
from .autoscaler import FleetAutoscaler
from .api_server import ApiServer
from .proc_replica import ProcReplica
from .wire import (FrameCorrupt, FrameOutOfOrder, FrameTooLarge,
                   WireClosed, WireError, WireTimeout)

__all__ = ["Config", "Predictor", "Tensor", "PrecisionType", "PlaceType",
           "create_predictor", "get_version", "ContinuousBatchingEngine",
           "ServedRequest", "AdmissionController", "EngineSupervisor",
           "ServingError", "RequestCancelled", "DeadlineExceeded",
           "RequestQuarantined", "Overloaded", "ReplicaFailed",
           "ServingFleet", "FleetReplica", "DisaggServingFleet",
           "FleetAutoscaler", "ApiServer", "ProcReplica",
           "WireError", "FrameCorrupt", "FrameTooLarge",
           "FrameOutOfOrder", "WireTimeout", "WireClosed"]


class PrecisionType(enum.Enum):
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class PlaceType(enum.Enum):
    UNK = -1
    CPU = 0
    GPU = 1  # accepted for compatibility; maps to the TPU/default device
    TPU = 2


def get_version():
    from ..version import full_version
    return full_version


class Config:
    """Predictor configuration (paddle_infer::Config parity)."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle convention: Config(model_dir) or Config(prog, params)
        self._model_dir = None
        self._prog_file = None
        self._params_file = None
        if prog_file is not None and params_file is None:
            # single argument: a directory (old paddle convention) or a
            # model file path
            if os.path.isdir(prog_file):
                self._model_dir = prog_file
            else:
                self._prog_file = prog_file
        else:
            self._prog_file = prog_file
            self._params_file = params_file
        self._precision = PrecisionType.Float32
        self._device = "default"  # cpu | default (tpu when present)
        self._ir_optim = True
        self._memory_optim = False
        self._layer = None
        self._disabled_glog = False

    # -- model location ----------------------------------------------------
    def set_model(self, prog_file, params_file=None):
        if params_file is None:
            self._model_dir = prog_file
        else:
            self._prog_file = prog_file
            self._params_file = params_file

    def set_prog_file(self, f):
        self._prog_file = f

    def set_params_file(self, f):
        self._params_file = f

    def model_dir(self):
        return self._model_dir

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    def set_layer(self, layer):
        """TPU extension: serve an in-memory Layer directly (the python
        program path; the reference's equivalent is passing a loaded
        program to the predictor)."""
        self._layer = layer

    def _model_path(self):
        """Base path (without extension) of the saved artifact."""
        if self._prog_file:
            base = self._prog_file
            for ext in (".pdmodel", ".pdexported"):
                if base.endswith(ext):
                    return base[:-len(ext)]
            return base
        if self._model_dir:
            # directory containing exactly one saved model
            cands = {f[:-len(".pdmeta")]
                     for f in os.listdir(self._model_dir)
                     if f.endswith(".pdmeta")}
            if len(cands) == 1:
                return os.path.join(self._model_dir, cands.pop())
            raise ValueError(
                f"model_dir {self._model_dir!r} must contain exactly one "
                f"saved model (found {sorted(cands)})")
        return None

    # -- device / precision ------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        """Compatibility alias: selects the default accelerator (TPU)."""
        self._device = "default"
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "default"

    def enable_xpu(self, *a, **k):
        self._device = "default"

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = int(n)

    def enable_tensorrt_engine(self, *a, **k):
        """No TensorRT on TPU; XLA plays the fused-subgraph role. The
        precision argument is honored."""
        prec = k.get("precision_mode")
        if prec is not None:
            self._precision = prec

    def tensorrt_engine_enabled(self):
        return False

    # -- graph options (XLA owns these; recorded for API parity) -----------
    def switch_ir_optim(self, on=True):
        self._ir_optim = bool(on)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, on=True):
        self._memory_optim = bool(on)

    def memory_optim_enabled(self):
        return self._memory_optim

    def switch_use_feed_fetch_ops(self, on=False):
        pass

    def switch_specify_input_names(self, on=True):
        pass

    def disable_glog_info(self):
        self._disabled_glog = True

    def glog_info_disabled(self):
        return self._disabled_glog

    def summary(self):
        rows = [("model_dir", self._model_dir),
                ("prog_file", self._prog_file),
                ("params_file", self._params_file),
                ("device", self._device),
                ("precision", self._precision.name),
                ("ir_optim", self._ir_optim),
                ("memory_optim", self._memory_optim)]
        w = max(len(k) for k, _ in rows)
        return "\n".join(f"{k:<{w}}  {v}" for k, v in rows)


class Tensor:
    """Zero-copy-style I/O handle bound to a predictor slot."""

    def __init__(self, name, owner, is_input):
        self._name = name
        self._owner = owner
        self._is_input = is_input

    @property
    def name(self):
        return self._name

    def reshape(self, shape):
        if not self._is_input:
            raise RuntimeError(f"{self._name} is an output handle")
        cur = self._owner._inputs.get(self._name)
        dtype = cur.dtype if cur is not None else np.float32
        self._owner._inputs[self._name] = jnp.zeros(tuple(shape), dtype)

    def copy_from_cpu(self, arr):
        if not self._is_input:
            raise RuntimeError(f"{self._name} is an output handle")
        self._owner._inputs[self._name] = jnp.asarray(arr)

    def copy_to_cpu(self):
        if self._is_input:
            return np.asarray(self._owner._inputs[self._name])
        outs = self._owner._outputs
        if outs is None:
            raise RuntimeError("run() has not been called")
        return np.asarray(outs[self._name])

    def shape(self):
        if self._is_input:
            a = self._owner._inputs.get(self._name)
        else:
            a = (self._owner._outputs or {}).get(self._name)
        return list(a.shape) if a is not None else []

    def type(self):
        if self._is_input:
            a = self._owner._inputs.get(self._name)
        else:
            a = (self._owner._outputs or {}).get(self._name)
        return str(a.dtype) if a is not None else "unknown"


class Predictor:
    """AnalysisPredictor parity: compiled execution of a saved model."""

    def __init__(self, config: Config, _shared=None):
        self._config = config
        self._inputs = {}
        self._outputs = None
        if _shared is not None:
            # clone(): share the loaded program/weights AND the
            # signature->compiled cache (the reference's Scope sharing;
            # clones must not redo XLA compilation)
            self._fn = _shared._fn
            self._input_names = (list(_shared._input_names)
                                 if _shared._input_names is not None
                                 else None)
            self._n_outputs = _shared._n_outputs
            self._can_cast = _shared._can_cast
            self._jitted = _shared._jitted
            return
        self._fn, self._input_names, self._n_outputs = self._load(config)
        # a serialized export pins its input dtypes; precision casting
        # is only possible on the retraceable in-memory layer path
        self._can_cast = config._layer is not None
        # jax.jit's own cache keys on shape/dtype/device, so one jitted
        # callable covers every signature (and clones share it)
        self._jitted = jax.jit(self._fn)

    # -- loading -----------------------------------------------------------
    def _load(self, config):
        if config._layer is not None:
            from ..framework.core import Tensor as PTensor
            layer = config._layer
            if hasattr(layer, "eval"):
                layer.eval()

            def fn(*xs):
                out = layer(*[PTensor(x) for x in xs])
                if isinstance(out, (list, tuple)):
                    return tuple(o.jax() if isinstance(o, PTensor) else o
                                 for o in out)
                return (out.jax() if isinstance(out, PTensor) else out,)
            return fn, None, None

        base = config._model_path()
        if base is None:
            raise ValueError("Config has no model path or layer")
        if not os.path.exists(base + ".pdexported"):
            raise FileNotFoundError(
                f"{base}.pdexported not found — save the model with "
                f"paddle_tpu.jit.save(layer, path, input_spec=...) so the "
                f"executable export artifact is written")
        from jax import export as jexport
        with open(base + ".pdexported", "rb") as f:
            exported = jexport.deserialize(bytearray(f.read()))
        n_in = len(exported.in_avals)
        names = [f"x{i}" for i in range(n_in)]

        def fn(*xs):
            out = exported.call(*xs)
            return out if isinstance(out, (list, tuple)) else (out,)
        return fn, names, None

    # -- handles -----------------------------------------------------------
    def get_input_names(self):
        if self._input_names is not None:
            return list(self._input_names)
        # handle-binding (insertion) order = the layer's positional
        # argument order
        return list(self._inputs.keys()) or ["x0"]

    def get_input_handle(self, name):
        if self._input_names is None and name not in self._inputs:
            self._inputs.setdefault(name, None)
        return Tensor(name, self, True)

    def get_input_tensor(self, name):  # legacy alias
        return self.get_input_handle(name)

    def get_output_names(self):
        if self._outputs is not None:
            return list(self._outputs.keys())  # out0..outN index order
        n = self._n_outputs or 1
        return [f"out{i}" for i in range(n)]

    def get_output_handle(self, name):
        return Tensor(name, self, False)

    def get_output_tensor(self, name):
        return self.get_output_handle(name)

    # -- execution ---------------------------------------------------------
    def _cast_inputs(self, xs):
        if self._can_cast and self._config._precision in (
                PrecisionType.Half, PrecisionType.Bfloat16):
            tgt = (jnp.float16 if self._config._precision
                   is PrecisionType.Half else jnp.bfloat16)
            xs = [x.astype(tgt) if jnp.issubdtype(x.dtype, jnp.floating)
                  else x for x in xs]
        return xs

    def run(self, inputs=None):
        """Execute. With ``inputs`` (list of arrays) returns outputs
        directly (paddle_infer 2.x convenience); otherwise uses the
        bound input handles and stores outputs for the output handles."""
        if inputs is not None:
            xs = [jnp.asarray(a) for a in inputs]
        else:
            names = (self._input_names
                     if self._input_names is not None
                     else list(self._inputs.keys()))
            missing = [n for n in names if self._inputs.get(n) is None]
            if missing:
                raise RuntimeError(f"inputs not set: {missing}")
            xs = [self._inputs[n] for n in names]
        xs = self._cast_inputs(xs)
        on_cpu = self._config._device == "cpu"
        if on_cpu:
            # disable_gpu(): actually execute on host, not just fetch
            cpu = jax.local_devices(backend="cpu")[0]
            xs = [jax.device_put(x, cpu) for x in xs]
        outs = self._jitted(*xs)
        if not isinstance(outs, (list, tuple)):
            outs = (outs,)
        outs = [jax.device_get(o) if on_cpu else o for o in outs]
        self._outputs = {f"out{i}": o for i, o in enumerate(outs)}
        self._n_outputs = len(outs)
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def clone(self):
        """New predictor sharing the loaded program and weights."""
        return Predictor(self._config, _shared=self)

    def try_shrink_memory(self):
        pass

    def clear_intermediate_tensor(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
