"""paddle.vision.transforms.functional — functional image ops on numpy HWC
arrays, PIL images, or Tensors (upstream
``python/paddle/vision/transforms/functional.py``, UNVERIFIED)."""

from __future__ import annotations

import numpy as np

from ...framework.core import Tensor

__all__ = ["to_tensor", "normalize", "resize", "pad", "crop", "center_crop",
           "hflip", "vflip", "rotate", "adjust_brightness",
           "adjust_contrast", "adjust_saturation", "adjust_hue",
           "to_grayscale", "erase"]


def _np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


def _like(arr, img):
    """Return arr in the caller's preferred container (Tensor in, Tensor
    out; otherwise numpy)."""
    if isinstance(img, Tensor):
        return Tensor(arr)
    return arr


def to_tensor(pic, data_format="CHW"):
    from . import to_tensor as _tt
    return _tt(pic, data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from . import normalize as _n
    return _n(img, mean, std, data_format, to_rgb)


def resize(img, size, interpolation="bilinear"):
    from . import Resize
    return Resize(size, interpolation)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _np(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    spec = [(pt, pb), (pl, pr)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return _like(np.pad(arr, spec, mode=mode, **kw), img)


def crop(img, top, left, height, width):
    arr = _np(img)
    return _like(arr[top:top + height, left:left + width].copy(), img)


def center_crop(img, output_size):
    from . import CenterCrop
    return CenterCrop(output_size)(img)


def hflip(img):
    arr = _np(img)
    return _like(arr[:, ::-1].copy(), img)


def vflip(img):
    arr = _np(img)
    return _like(arr[::-1].copy(), img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate by `angle` degrees counter-clockwise via inverse affine
    sampling (vectorized gather — no scipy dependency)."""
    arr = _np(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    theta = np.deg2rad(angle)
    cos, sin = np.cos(theta), np.sin(theta)
    if expand:
        corners = np.array([[-cx, -cy], [w - 1 - cx, -cy],
                            [-cx, h - 1 - cy], [w - 1 - cx, h - 1 - cy]])
        rot = corners @ np.array([[cos, sin], [-sin, cos]])
        nw = int(np.ceil(rot[:, 0].max() - rot[:, 0].min() + 1))
        nh = int(np.ceil(rot[:, 1].max() - rot[:, 1].min() + 1))
        ocy, ocx = (nh - 1) / 2.0, (nw - 1) / 2.0
    else:
        nh, nw, ocy, ocx = h, w, cy, cx
    yy, xx = np.meshgrid(np.arange(nh, dtype=np.float64),
                         np.arange(nw, dtype=np.float64), indexing="ij")
    # inverse map: output pixel -> source pixel (rotate by -angle)
    dx, dy = xx - ocx, yy - ocy
    sx = cos * dx - sin * dy + cx
    sy = sin * dx + cos * dy + cy
    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(int)
        y0 = np.floor(sy).astype(int)
        wx, wy = sx - x0, sy - y0

        def g(yi, xi):
            valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            yi, xi = np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)
            v = arr[yi, xi].astype(np.float64)
            if arr.ndim == 3:
                valid = valid[..., None]
            return np.where(valid, v, float(fill))

        wyx = ((1 - wy) * (1 - wx), (1 - wy) * wx, wy * (1 - wx), wy * wx)
        if arr.ndim == 3:
            wyx = tuple(w_[..., None] for w_ in wyx)
        out = (g(y0, x0) * wyx[0] + g(y0, x0 + 1) * wyx[1]
               + g(y0 + 1, x0) * wyx[2] + g(y0 + 1, x0 + 1) * wyx[3])
    else:  # nearest
        yi = np.round(sy).astype(int)
        xi = np.round(sx).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yi, xi = np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)
        out = arr[yi, xi].astype(np.float64)
        mask = valid if arr.ndim == 2 else valid[..., None]
        out = out * mask + fill * (~mask)
    return _like(out.astype(arr.dtype), img)


def adjust_brightness(img, brightness_factor):
    src = _np(img)
    hi = 255.0 if src.dtype == np.uint8 else 1.0
    out = np.clip(src.astype(np.float32) * brightness_factor, 0, hi)
    return _like(out.astype(src.dtype), img)


def adjust_contrast(img, contrast_factor):
    src = _np(img)
    hi = 255.0 if src.dtype == np.uint8 else 1.0
    arr = src.astype(np.float32)
    mean = _rgb_to_gray(arr).mean()
    out = np.clip((arr - mean) * contrast_factor + mean, 0, hi)
    return _like(out.astype(src.dtype), img)


def adjust_saturation(img, saturation_factor):
    src = _np(img)
    hi = 255.0 if src.dtype == np.uint8 else 1.0
    arr = src.astype(np.float32)
    gray = _rgb_to_gray(arr)[..., None]
    out = np.clip(gray + (arr - gray) * saturation_factor, 0, hi)
    return _like(out.astype(src.dtype), img)


def _rgb_to_gray(arr):
    if arr.ndim == 2 or arr.shape[-1] == 1:
        return arr.reshape(arr.shape[:2])
    return arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] revolutions) via RGB→HSV→RGB
    in numpy."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    src = _np(img)
    dtype = src.dtype
    arr = src.astype(np.float32) / (255.0 if dtype == np.uint8 else 1.0)
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = arr[..., :3].max(-1)
    minc = arr[..., :3].min(-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    h = np.select(
        [maxc == r, maxc == g],
        [((g - b) / dz) % 6.0, (b - r) / dz + 2.0],
        default=(r - g) / dz + 4.0) / 6.0
    h = np.where(delta > 0, h, 0.0)
    h = (h + hue_factor) % 1.0
    # HSV -> RGB
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(int) % 6
    rgb = np.choose(i[..., None], [
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = rgb * (255.0 if dtype == np.uint8 else 1.0)
    return _like(out.astype(dtype), img)


def to_grayscale(img, num_output_channels=1):
    arr = _np(img)
    gray = _rgb_to_gray(arr.astype(np.float32))
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return _like(out.astype(arr.dtype), img)


def erase(img, i, j, h, w, v, inplace=False):
    arr = _np(img)
    # Tensor data is an immutable jax buffer — _np() is a read-only view,
    # so a copy is required even for inplace=True (the returned Tensor is
    # the mutation)
    if not inplace or isinstance(img, Tensor):
        arr = arr.copy()
    # paddle semantics: Tensor input is CHW, ndarray/PIL input is HWC.
    # Keying on the input type (not a shape[-1] in (1,3,4) guess) means a
    # CHW image whose width happens to be 1/3/4 is not misclassified.
    # Batched (ndim>=4) arrays are NCHW either way.
    if (isinstance(img, Tensor) and arr.ndim >= 3) or arr.ndim >= 4:
        v_arr = np.asarray(v, dtype=arr.dtype)
        if v_arr.ndim == 1:  # per-channel values -> broadcast over H, W
            v_arr = v_arr.reshape(-1, 1, 1)
        arr[..., i:i + h, j:j + w] = v_arr
    else:  # HWC or 2-D
        arr[i:i + h, j:j + w] = v
    return _like(arr, img)


def _ensure_hwc(arr):
    """uint8/float HWC with an explicit channel dim; returns (a3, had_c)."""
    if arr.ndim == 2:
        return arr[:, :, None], False
    return arr, True


def _restore(out, arr, had_c, img):
    """Exit twin of _ensure_hwc: restore dtype (rounding uint8) and the
    original channel layout, rewrap in the caller's container."""
    if arr.dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    else:
        out = out.astype(arr.dtype)
    if not had_c:
        out = out[..., 0]
    return _like(out, img)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Affine-warp an HWC image (paddle.vision.transforms.functional
    parity): rotate by ``angle`` deg about ``center``, then shear,
    scale, translate. Inverse-warp via scipy.ndimage."""
    import math

    from scipy import ndimage

    arr = _np(img)
    a3, had_c = _ensure_hwc(arr)
    h, w = a3.shape[:2]
    cy, cx = ((h - 1) * 0.5, (w - 1) * 0.5) if center is None else \
        (center[1], center[0])
    rot = math.radians(angle)
    sx = math.radians(shear[0] if isinstance(shear, (list, tuple))
                      else shear)
    sy = math.radians(shear[1] if isinstance(shear, (list, tuple))
                      and len(shear) > 1 else 0.0)
    # forward matrix in (x, y): R @ Shear @ Scale
    a = scale * (math.cos(rot + sy) / math.cos(sy))
    b = scale * (math.cos(rot + sy) * math.tan(sx) / math.cos(sy)
                 - math.sin(rot))
    c = scale * (math.sin(rot + sy) / math.cos(sy))
    d = scale * (math.sin(rot + sy) * math.tan(sx) / math.cos(sy)
                 + math.cos(rot))
    fwd = np.array([[a, b], [c, d]], np.float64)
    inv = np.linalg.inv(fwd)
    tx, ty = (translate if translate is not None else (0, 0))
    # output (x,y) -> input: inv @ (p - center - t) + center
    offset_xy = np.array([cx + tx, cy + ty])
    order = 1 if interpolation in ("bilinear", "linear") else 0
    # scipy works in (row, col) = (y, x): build the matching matrix
    inv_rc = inv[::-1, ::-1]
    off_rc = np.array([cy, cx]) - inv_rc @ np.array([offset_xy[1],
                                                     offset_xy[0]])
    out = np.stack([
        ndimage.affine_transform(a3[..., ch].astype(np.float32), inv_rc,
                                 offset=off_rc, order=order,
                                 mode="constant", cval=float(
                                     fill[ch] if isinstance(
                                         fill, (list, tuple)) else fill))
        for ch in range(a3.shape[2])], axis=-1)
    return _restore(out, arr, had_c, img)


def _perspective_coeffs(startpoints, endpoints):
    """8 homography coefficients mapping endpoints -> startpoints."""
    mat = []
    for (ex, ey), (sx_, sy_) in zip(endpoints, startpoints):
        mat.append([ex, ey, 1, 0, 0, 0, -sx_ * ex, -sx_ * ey])
        mat.append([0, 0, 0, ex, ey, 1, -sy_ * ex, -sy_ * ey])
    a_mat = np.asarray(mat, np.float64)
    b_vec = np.asarray([c for p in startpoints for c in p], np.float64)
    return np.linalg.lstsq(a_mat, b_vec, rcond=None)[0]


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Perspective-warp: the quad ``startpoints`` maps to ``endpoints``."""
    from scipy import ndimage

    arr = _np(img)
    a3, had_c = _ensure_hwc(arr)
    h, w = a3.shape[:2]
    co = _perspective_coeffs(startpoints, endpoints)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
    denom = co[6] * xx + co[7] * yy + 1.0
    src_x = (co[0] * xx + co[1] * yy + co[2]) / denom
    src_y = (co[3] * xx + co[4] * yy + co[5]) / denom

    def _snap(v, hi):
        # lstsq noise can push border coordinates epsilon outside the
        # domain, which scipy's constant mode would blank to cval
        v = np.where((v > -1e-6) & (v < 0), 0.0, v)
        return np.where((v > hi) & (v < hi + 1e-6), hi, v)
    src_x = _snap(src_x, w - 1.0)
    src_y = _snap(src_y, h - 1.0)
    order = 1 if interpolation in ("bilinear", "linear") else 0
    out = np.stack([
        ndimage.map_coordinates(a3[..., ch].astype(np.float32),
                                [src_y, src_x], order=order,
                                mode="constant", cval=float(
                                    fill[ch] if isinstance(
                                        fill, (list, tuple)) else fill))
        for ch in range(a3.shape[2])], axis=-1)
    return _restore(out, arr, had_c, img)


def _peak(arr):
    return 255.0 if arr.dtype == np.uint8 else 1.0


def invert(img):
    arr = _np(img)
    return _like((_peak(arr) - arr).astype(arr.dtype), img)


def posterize(img, bits):
    arr = _np(img)
    if arr.dtype != np.uint8:
        raise ValueError("posterize expects a uint8 image")
    mask = 255 - (2 ** (8 - int(bits)) - 1)
    return _like((arr & mask).astype(np.uint8), img)


def solarize(img, threshold):
    arr = _np(img)
    peak = _peak(arr)
    return _like(np.where(arr >= threshold, peak - arr,
                          arr).astype(arr.dtype), img)


def adjust_sharpness(img, sharpness_factor):
    """PIL-convention sharpness: blend with a 3x3 smoothed copy;
    factor 0 = smoothed, 1 = original, >1 = sharpened."""
    from scipy import ndimage

    arr = _np(img)
    a3, had_c = _ensure_hwc(arr)
    kernel = np.array([[1, 1, 1], [1, 5, 1], [1, 1, 1]], np.float32) / 13
    smooth = np.stack([
        ndimage.convolve(a3[..., ch].astype(np.float32), kernel,
                         mode="nearest")
        for ch in range(a3.shape[2])], axis=-1)
    # PIL keeps the 1px border of the original
    sm = a3.astype(np.float32).copy()
    sm[1:-1, 1:-1] = smooth[1:-1, 1:-1]
    out = sm + float(sharpness_factor) * (a3.astype(np.float32) - sm)
    return _restore(out, arr, had_c, img)


def gaussian_blur(img, kernel_size, sigma=None):
    from scipy import ndimage

    arr = _np(img)
    a3, had_c = _ensure_hwc(arr)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    if sigma is None:
        sigma = tuple(0.3 * ((k - 1) * 0.5 - 1) + 0.8
                      for k in kernel_size)
    elif isinstance(sigma, (int, float)):
        sigma = (float(sigma), float(sigma))
    out = np.stack([
        ndimage.gaussian_filter(a3[..., ch].astype(np.float32),
                                sigma=sigma[::-1], mode="nearest")
        for ch in range(a3.shape[2])], axis=-1)
    return _restore(out, arr, had_c, img)


def equalize(img):
    """Per-channel histogram equalization (PIL convention; uint8 only)."""
    arr = _np(img)
    if arr.dtype != np.uint8:
        raise ValueError("equalize expects a uint8 image")
    a3, had_c = _ensure_hwc(arr)
    out = a3.copy()
    flat = out.reshape(-1, out.shape[-1])
    for ch in range(flat.shape[1]):
        hist = np.bincount(flat[:, ch], minlength=256)
        cdf = hist.cumsum()
        nz = cdf[cdf > 0]
        if nz.size == 0:
            continue
        lut = np.clip((cdf - nz[0]) * 255.0 / max(cdf[-1] - nz[0], 1),
                      0, 255).astype(np.uint8)
        flat[:, ch] = lut[flat[:, ch]]
    out = flat.reshape(a3.shape)
    if not had_c:
        out = out[..., 0]
    return _like(out, img)


__all__ += ["affine", "perspective", "invert", "posterize", "solarize",
            "adjust_sharpness", "gaussian_blur", "equalize"]
