"""paddle.vision.transforms.functional — functional image ops on numpy HWC
arrays, PIL images, or Tensors (upstream
``python/paddle/vision/transforms/functional.py``, UNVERIFIED)."""

from __future__ import annotations

import numpy as np

from ...framework.core import Tensor

__all__ = ["to_tensor", "normalize", "resize", "pad", "crop", "center_crop",
           "hflip", "vflip", "rotate", "adjust_brightness",
           "adjust_contrast", "adjust_saturation", "adjust_hue",
           "to_grayscale", "erase"]


def _np(img):
    if isinstance(img, Tensor):
        return np.asarray(img._data)
    return np.asarray(img)


def _like(arr, img):
    """Return arr in the caller's preferred container (Tensor in, Tensor
    out; otherwise numpy)."""
    if isinstance(img, Tensor):
        return Tensor(arr)
    return arr


def to_tensor(pic, data_format="CHW"):
    from . import to_tensor as _tt
    return _tt(pic, data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from . import normalize as _n
    return _n(img, mean, std, data_format, to_rgb)


def resize(img, size, interpolation="bilinear"):
    from . import Resize
    return Resize(size, interpolation)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _np(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    spec = [(pt, pb), (pl, pr)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return _like(np.pad(arr, spec, mode=mode, **kw), img)


def crop(img, top, left, height, width):
    arr = _np(img)
    return _like(arr[top:top + height, left:left + width].copy(), img)


def center_crop(img, output_size):
    from . import CenterCrop
    return CenterCrop(output_size)(img)


def hflip(img):
    arr = _np(img)
    return _like(arr[:, ::-1].copy(), img)


def vflip(img):
    arr = _np(img)
    return _like(arr[::-1].copy(), img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate by `angle` degrees counter-clockwise via inverse affine
    sampling (vectorized gather — no scipy dependency)."""
    arr = _np(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    theta = np.deg2rad(angle)
    cos, sin = np.cos(theta), np.sin(theta)
    if expand:
        corners = np.array([[-cx, -cy], [w - 1 - cx, -cy],
                            [-cx, h - 1 - cy], [w - 1 - cx, h - 1 - cy]])
        rot = corners @ np.array([[cos, sin], [-sin, cos]])
        nw = int(np.ceil(rot[:, 0].max() - rot[:, 0].min() + 1))
        nh = int(np.ceil(rot[:, 1].max() - rot[:, 1].min() + 1))
        ocy, ocx = (nh - 1) / 2.0, (nw - 1) / 2.0
    else:
        nh, nw, ocy, ocx = h, w, cy, cx
    yy, xx = np.meshgrid(np.arange(nh, dtype=np.float64),
                         np.arange(nw, dtype=np.float64), indexing="ij")
    # inverse map: output pixel -> source pixel (rotate by -angle)
    dx, dy = xx - ocx, yy - ocy
    sx = cos * dx - sin * dy + cx
    sy = sin * dx + cos * dy + cy
    if interpolation == "bilinear":
        x0 = np.floor(sx).astype(int)
        y0 = np.floor(sy).astype(int)
        wx, wy = sx - x0, sy - y0

        def g(yi, xi):
            valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            yi, xi = np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)
            v = arr[yi, xi].astype(np.float64)
            if arr.ndim == 3:
                valid = valid[..., None]
            return np.where(valid, v, float(fill))

        wyx = ((1 - wy) * (1 - wx), (1 - wy) * wx, wy * (1 - wx), wy * wx)
        if arr.ndim == 3:
            wyx = tuple(w_[..., None] for w_ in wyx)
        out = (g(y0, x0) * wyx[0] + g(y0, x0 + 1) * wyx[1]
               + g(y0 + 1, x0) * wyx[2] + g(y0 + 1, x0 + 1) * wyx[3])
    else:  # nearest
        yi = np.round(sy).astype(int)
        xi = np.round(sx).astype(int)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yi, xi = np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)
        out = arr[yi, xi].astype(np.float64)
        mask = valid if arr.ndim == 2 else valid[..., None]
        out = out * mask + fill * (~mask)
    return _like(out.astype(arr.dtype), img)


def adjust_brightness(img, brightness_factor):
    src = _np(img)
    hi = 255.0 if src.dtype == np.uint8 else 1.0
    out = np.clip(src.astype(np.float32) * brightness_factor, 0, hi)
    return _like(out.astype(src.dtype), img)


def adjust_contrast(img, contrast_factor):
    src = _np(img)
    hi = 255.0 if src.dtype == np.uint8 else 1.0
    arr = src.astype(np.float32)
    mean = _rgb_to_gray(arr).mean()
    out = np.clip((arr - mean) * contrast_factor + mean, 0, hi)
    return _like(out.astype(src.dtype), img)


def adjust_saturation(img, saturation_factor):
    src = _np(img)
    hi = 255.0 if src.dtype == np.uint8 else 1.0
    arr = src.astype(np.float32)
    gray = _rgb_to_gray(arr)[..., None]
    out = np.clip(gray + (arr - gray) * saturation_factor, 0, hi)
    return _like(out.astype(src.dtype), img)


def _rgb_to_gray(arr):
    if arr.ndim == 2 or arr.shape[-1] == 1:
        return arr.reshape(arr.shape[:2])
    return arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] revolutions) via RGB→HSV→RGB
    in numpy."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    src = _np(img)
    dtype = src.dtype
    arr = src.astype(np.float32) / (255.0 if dtype == np.uint8 else 1.0)
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = arr[..., :3].max(-1)
    minc = arr[..., :3].min(-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    h = np.select(
        [maxc == r, maxc == g],
        [((g - b) / dz) % 6.0, (b - r) / dz + 2.0],
        default=(r - g) / dz + 4.0) / 6.0
    h = np.where(delta > 0, h, 0.0)
    h = (h + hue_factor) % 1.0
    # HSV -> RGB
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(int) % 6
    rgb = np.choose(i[..., None], [
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = rgb * (255.0 if dtype == np.uint8 else 1.0)
    return _like(out.astype(dtype), img)


def to_grayscale(img, num_output_channels=1):
    arr = _np(img)
    gray = _rgb_to_gray(arr.astype(np.float32))
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return _like(out.astype(arr.dtype), img)


def erase(img, i, j, h, w, v, inplace=False):
    arr = _np(img)
    # Tensor data is an immutable jax buffer — _np() is a read-only view,
    # so a copy is required even for inplace=True (the returned Tensor is
    # the mutation)
    if not inplace or isinstance(img, Tensor):
        arr = arr.copy()
    # paddle semantics: Tensor input is CHW, ndarray/PIL input is HWC.
    # Keying on the input type (not a shape[-1] in (1,3,4) guess) means a
    # CHW image whose width happens to be 1/3/4 is not misclassified.
    # Batched (ndim>=4) arrays are NCHW either way.
    if (isinstance(img, Tensor) and arr.ndim >= 3) or arr.ndim >= 4:
        v_arr = np.asarray(v, dtype=arr.dtype)
        if v_arr.ndim == 1:  # per-channel values -> broadcast over H, W
            v_arr = v_arr.reshape(-1, 1, 1)
        arr[..., i:i + h, j:j + w] = v_arr
    else:  # HWC or 2-D
        arr[i:i + h, j:j + w] = v
    return _like(arr, img)
