"""Basic vision transforms (python/paddle/vision/transforms parity,
UNVERIFIED) operating on numpy HWC arrays / Tensors."""

from __future__ import annotations

import numpy as np

from ...framework.core import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose", "to_tensor",
           "normalize"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


def to_tensor(pic, data_format="CHW"):
    src = np.asarray(pic)
    arr = src.astype(np.float32)
    # scale to [0, 1] by dtype (not by content — a dark image must scale
    # the same as a bright one). Only uint8/uint16 have an unambiguous
    # pixel range; wider int dtypes (e.g. PIL mode 'I') pass through
    # unscaled, matching upstream/torchvision.
    if src.dtype == np.uint8:
        arr = arr / 255.0
    elif src.dtype == np.uint16:
        arr = arr / 65535.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, pic):
        return to_tensor(pic, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._data)
    else:
        arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = mean if isinstance(mean, (list, tuple)) else [mean] * 3
        self.std = std if isinstance(std, (list, tuple)) else [std] * 3
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


_RESIZE_METHODS = {"nearest": "nearest", "bilinear": "linear",
                   "linear": "linear", "bicubic": "cubic", "cubic": "cubic",
                   "lanczos": "lanczos3", "area": "linear"}


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.method = _RESIZE_METHODS[interpolation]

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = img._data if isinstance(img, Tensor) else jnp.asarray(img)
        hwc = arr.ndim == 3 and arr.shape[-1] in (1, 3, 4)
        if hwc:
            out_shape = self.size + (arr.shape[-1],)
        else:
            out_shape = arr.shape[:-2] + self.size
        if self.method == "nearest":
            return Tensor(jax.image.resize(arr, out_shape, "nearest"))
        out = jax.image.resize(arr.astype(jnp.float32), out_shape,
                               self.method)
        if jnp.issubdtype(arr.dtype, jnp.integer):
            out = jnp.round(out)  # truncation would bias pixels downward
        return Tensor(out.astype(arr.dtype))


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        h, w = arr.shape[:2] if arr.shape[-1] in (1, 3, 4) else \
            arr.shape[-2:]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
            return Tensor(arr[i:i + th, j:j + tw])
        return Tensor(arr[..., i:i + th, j:j + tw])


class RandomCrop:
    def __init__(self, size, padding=0, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        hwc = arr.ndim != 3 or arr.shape[-1] in (1, 3, 4)
        if self.padding:
            p = self.padding
            if hwc:
                pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            else:
                pad = [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)]
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2] if hwc else arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        if hwc:
            return Tensor(arr[i:i + th, j:j + tw])
        return Tensor(arr[..., i:i + th, j:j + tw])


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        if np.random.rand() < self.prob:
            arr = arr[:, ::-1].copy()
        return Tensor(arr)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        return Tensor(arr.transpose(self.order))


from . import functional  # noqa: E402
from . import functional as F  # noqa: E402

__all__ += ["functional", "RandomVerticalFlip", "Pad", "ColorJitter",
            "Grayscale", "RandomRotation", "RandomResizedCrop",
            "BrightnessTransform", "ContrastTransform",
            "SaturationTransform", "HueTransform", "RandomErasing"]


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return F.vflip(img)
        return Tensor(np.asarray(img._data)) if isinstance(img, Tensor) \
            else img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BrightnessTransform):
    def __call__(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BrightnessTransform):
    def __call__(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class ColorJitter:
    """Randomly jitter brightness/contrast/saturation/hue, applied in
    random order (upstream semantics)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def __call__(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i](img)
        return img


class Grayscale:
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class RandomResizedCrop:
    """Crop a random area/aspect-ratio patch and resize it (the Inception
    training crop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            logr = np.random.uniform(np.log(self.ratio[0]),
                                     np.log(self.ratio[1]))
            ar = np.exp(logr)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                patch = arr[i:i + ch, j:j + cw]
                break
        else:  # fallback: center crop to min side
            s = min(h, w)
            i, j = (h - s) // 2, (w - s) // 2
            patch = arr[i:i + s, j:j + s]
        return Resize(self.size, self.interpolation)(patch)


class RandomErasing:
    """Randomly erase a rectangle (Cutout/RandomErasing regularization)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        # same convention as F.erase: Tensor is CHW, ndarray/PIL is HWC,
        # and batched (ndim>=4) arrays are NCHW either way
        chw = (isinstance(img, Tensor) and arr.ndim >= 3) or arr.ndim >= 4
        h, w = (arr.shape[-2:] if chw else arr.shape[:2])
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                return F.erase(img, i, j, eh, ew, self.value, self.inplace)
        return img


class RandomAffine:
    """Random rotation/translation/scale/shear (transform parity)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, (int, float)) else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        h, w = arr.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate is not None:
            tx = int(np.random.uniform(-self.translate[0],
                                       self.translate[0]) * w)
            ty = int(np.random.uniform(-self.translate[1],
                                       self.translate[1]) * h)
        sc = np.random.uniform(*self.scale) if self.scale else 1.0
        # shear accepts scalar s (x in [-s, s]), [lo, hi] (x range), or
        # [xlo, xhi, ylo, yhi] (paddle/torchvision forms)
        if self.shear is None:
            sh = 0.0
        elif isinstance(self.shear, (int, float)):
            sh = np.random.uniform(-self.shear, self.shear)
        elif len(self.shear) == 2:
            sh = np.random.uniform(self.shear[0], self.shear[1])
        else:
            sh = (np.random.uniform(self.shear[0], self.shear[1]),
                  np.random.uniform(self.shear[2], self.shear[3]))
        return F.affine(img, angle, (tx, ty), sc, sh,
                        self.interpolation, self.fill, self.center)


class RandomPerspective:
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        dx = int(d * w / 2)
        dy = int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dx + 1),
                np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1)),
               (np.random.randint(0, dx + 1),
                h - 1 - np.random.randint(0, dy + 1))]
        return F.perspective(img, start, end, self.interpolation,
                             self.fill)


class GaussianBlur:
    def __init__(self, kernel_size=3, sigma=(0.1, 2.0), keys=None):
        self.kernel_size = kernel_size
        self.sigma = sigma

    def __call__(self, img):
        s = np.random.uniform(*self.sigma) if isinstance(
            self.sigma, (list, tuple)) else self.sigma
        return F.gaussian_blur(img, self.kernel_size, s)


class _RandomPhotometric:
    op = None

    def __init__(self, prob=0.5, keys=None, **kw):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        return self._apply(img)


class RandomInvert(_RandomPhotometric):
    def _apply(self, img):
        return F.invert(img)


class RandomPosterize(_RandomPhotometric):
    def __init__(self, bits=4, prob=0.5, keys=None):
        super().__init__(prob)
        self.bits = bits

    def _apply(self, img):
        return F.posterize(img, self.bits)


class RandomSolarize(_RandomPhotometric):
    def __init__(self, threshold=128, prob=0.5, keys=None):
        super().__init__(prob)
        self.threshold = threshold

    def _apply(self, img):
        return F.solarize(img, self.threshold)


class RandomAdjustSharpness(_RandomPhotometric):
    def __init__(self, sharpness_factor=2.0, prob=0.5, keys=None):
        super().__init__(prob)
        self.factor = sharpness_factor

    def _apply(self, img):
        return F.adjust_sharpness(img, self.factor)


def _aug_op(name, img, mag):
    """One augmentation primitive at signed magnitude ``mag``."""
    if name == "identity":
        return img
    if name == "shear_x":
        return F.affine(img, 0, (0, 0), 1.0, np.degrees(np.arctan(mag)))
    if name == "shear_y":
        return F.affine(img, 0, (0, 0), 1.0, (0.0, np.degrees(
            np.arctan(mag))))
    if name == "translate_x":
        w = np.asarray(img._data if isinstance(img, Tensor)
                       else img).shape[1]
        return F.affine(img, 0, (int(mag * w), 0), 1.0, 0.0)
    if name == "translate_y":
        h = np.asarray(img._data if isinstance(img, Tensor)
                       else img).shape[0]
        return F.affine(img, 0, (0, int(mag * h)), 1.0, 0.0)
    if name == "rotate":
        return F.affine(img, mag, (0, 0), 1.0, 0.0)
    if name == "brightness":
        return F.adjust_brightness(img, 1.0 + mag)
    if name == "contrast":
        return F.adjust_contrast(img, 1.0 + mag)
    if name == "color":
        return F.adjust_saturation(img, 1.0 + mag)
    if name == "sharpness":
        return F.adjust_sharpness(img, 1.0 + mag)
    if name == "posterize":
        return F.posterize(img, max(1, int(8 - abs(mag))))
    if name == "solarize":
        return F.solarize(img, int(256 - abs(mag)))
    if name == "equalize":
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        if arr.dtype != np.uint8:
            return img
        return F.equalize(img)
    if name == "invert":
        return F.invert(img)
    return img


_RANDAUG_SPACE = [
    ("identity", 0.0), ("shear_x", 0.3), ("shear_y", 0.3),
    ("translate_x", 0.45), ("translate_y", 0.45), ("rotate", 30.0),
    ("brightness", 0.9), ("contrast", 0.9), ("color", 0.9),
    ("sharpness", 0.9), ("posterize", 4.0), ("solarize", 256.0),
    ("equalize", 0.0),
]


class RandAugment:
    """RandAugment (Cubuk et al.): ``num_ops`` random ops at shared
    ``magnitude`` out of ``num_magnitude_bins`` (paddle parity)."""

    def __init__(self, num_ops=2, magnitude=9, num_magnitude_bins=31,
                 interpolation="nearest", fill=0, keys=None):
        self.num_ops = int(num_ops)
        self.magnitude = int(magnitude)
        self.bins = int(num_magnitude_bins)

    def __call__(self, img):
        for _ in range(self.num_ops):
            name, max_mag = _RANDAUG_SPACE[
                np.random.randint(len(_RANDAUG_SPACE))]
            frac = self.magnitude / max(self.bins - 1, 1)
            mag = max_mag * frac
            if name in ("shear_x", "shear_y", "translate_x",
                        "translate_y", "rotate", "brightness",
                        "contrast", "color", "sharpness"):
                if np.random.rand() < 0.5:
                    mag = -mag
            img = _aug_op(name, img, mag)
        return img


# (op, probability, magnitude) triples — the ImageNet AutoAugment policy
_AA_IMAGENET = [
    (("posterize", 0.4, 8), ("rotate", 0.6, 9)),
    (("solarize", 0.6, 5), ("equalize", 0.6, 0)),
    (("equalize", 0.8, 0), ("equalize", 0.6, 0)),
    (("posterize", 0.6, 7), ("posterize", 0.6, 6)),
    (("equalize", 0.4, 0), ("solarize", 0.2, 4)),
    (("equalize", 0.4, 0), ("rotate", 0.8, 8)),
    (("solarize", 0.6, 3), ("equalize", 0.6, 0)),
    (("posterize", 0.8, 5), ("equalize", 1.0, 0)),
    (("rotate", 0.2, 3), ("solarize", 0.6, 8)),
    (("equalize", 0.6, 0), ("posterize", 0.4, 6)),
    (("rotate", 0.8, 8), ("color", 0.4, 0)),
    (("rotate", 0.4, 9), ("equalize", 0.6, 0)),
    (("equalize", 0.0, 0), ("equalize", 0.8, 0)),
    (("invert", 0.6, 0), ("equalize", 1.0, 0)),
    (("color", 0.6, 4), ("contrast", 1.0, 8)),
]


class AutoAugment:
    """AutoAugment with the ImageNet policy (paddle parity: policy
    subpolicies of two (op, prob, magnitude) steps)."""

    def __init__(self, policy="imagenet", interpolation="nearest",
                 fill=0, keys=None):
        if policy != "imagenet":
            import warnings
            warnings.warn(f"AutoAugment policy {policy!r} not available; "
                          "using the imagenet policy")
        self.policy = _AA_IMAGENET

    def __call__(self, img):
        sub = self.policy[np.random.randint(len(self.policy))]
        for name, prob, mag_bin in sub:
            if np.random.rand() > prob:
                continue
            max_mag = dict(_RANDAUG_SPACE).get(name, 0.0)
            mag = max_mag * mag_bin / 10.0
            # signed magnitude for every geometric AND enhance op
            # (torchvision/paddle convention: factor = 1 ± 0.9*m/10 —
            # the weakening side must be reachable)
            if name in ("rotate", "shear_x", "shear_y", "translate_x",
                        "translate_y", "brightness", "contrast",
                        "color", "sharpness") and np.random.rand() < 0.5:
                mag = -mag
            # _aug_op's posterize/solarize take the REDUCTION amount
            # (bits = 8-|mag|, threshold = 256-|mag|)
            if name == "posterize":
                mag = mag_bin * 4 / 10.0
            if name == "solarize":
                mag = mag_bin * 256 / 10.0
            img = _aug_op(name, img, mag)
        return img


__all__ += ["RandomAffine", "RandomPerspective", "GaussianBlur",
            "RandomInvert", "RandomPosterize", "RandomSolarize",
            "RandomAdjustSharpness", "RandAugment", "AutoAugment"]


class BaseTransform:
    """paddle.vision.transforms.BaseTransform parity: keys-aware
    transform base. Subclasses implement ``_apply_image`` (and
    optionally ``_apply_boxes`` / ``_apply_mask``); __call__ maps the
    right _apply_* over the inputs per ``keys``."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _get_params(self, inputs):
        return None

    def __call__(self, inputs):
        single = not isinstance(inputs, (tuple, list))
        items = (inputs,) if single else tuple(inputs)
        self.params = self._get_params(items)
        out = []
        for key, item in zip(self.keys, items):
            fn = getattr(self, f"_apply_{key}", None)
            out.append(fn(item) if fn is not None else item)
        # inputs beyond len(keys) (e.g. the label in (img, label)) pass
        # through untouched — upstream contract
        out.extend(items[len(self.keys):])
        return out[0] if single else tuple(out)

    def _apply_image(self, image):
        raise NotImplementedError


# functional names at the transforms level (upstream import-path parity:
# paddle.vision.transforms.resize IS transforms.functional.resize)
from .functional import (resize, pad, crop, center_crop, hflip,  # noqa
                         vflip, rotate, adjust_brightness,
                         adjust_contrast, adjust_hue, to_grayscale,
                         erase, affine, perspective)

__all__ += ["BaseTransform", "resize", "pad", "crop", "center_crop",
            "hflip", "vflip", "rotate", "adjust_brightness",
            "adjust_contrast", "adjust_hue", "to_grayscale", "erase",
            "affine", "perspective"]
