"""Basic vision transforms (python/paddle/vision/transforms parity,
UNVERIFIED) operating on numpy HWC arrays / Tensors."""

from __future__ import annotations

import numpy as np

from ...framework.core import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose", "to_tensor",
           "normalize"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


def to_tensor(pic, data_format="CHW"):
    src = np.asarray(pic)
    arr = src.astype(np.float32)
    # scale to [0, 1] by dtype (not by content — a dark image must scale
    # the same as a bright one). Only uint8/uint16 have an unambiguous
    # pixel range; wider int dtypes (e.g. PIL mode 'I') pass through
    # unscaled, matching upstream/torchvision.
    if src.dtype == np.uint8:
        arr = arr / 255.0
    elif src.dtype == np.uint16:
        arr = arr / 65535.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, pic):
        return to_tensor(pic, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._data)
    else:
        arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = mean if isinstance(mean, (list, tuple)) else [mean] * 3
        self.std = std if isinstance(std, (list, tuple)) else [std] * 3
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


_RESIZE_METHODS = {"nearest": "nearest", "bilinear": "linear",
                   "linear": "linear", "bicubic": "cubic", "cubic": "cubic",
                   "lanczos": "lanczos3", "area": "linear"}


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.method = _RESIZE_METHODS[interpolation]

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = img._data if isinstance(img, Tensor) else jnp.asarray(img)
        hwc = arr.ndim == 3 and arr.shape[-1] in (1, 3, 4)
        if hwc:
            out_shape = self.size + (arr.shape[-1],)
        else:
            out_shape = arr.shape[:-2] + self.size
        if self.method == "nearest":
            return Tensor(jax.image.resize(arr, out_shape, "nearest"))
        out = jax.image.resize(arr.astype(jnp.float32), out_shape,
                               self.method)
        if jnp.issubdtype(arr.dtype, jnp.integer):
            out = jnp.round(out)  # truncation would bias pixels downward
        return Tensor(out.astype(arr.dtype))


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        h, w = arr.shape[:2] if arr.shape[-1] in (1, 3, 4) else \
            arr.shape[-2:]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
            return Tensor(arr[i:i + th, j:j + tw])
        return Tensor(arr[..., i:i + th, j:j + tw])


class RandomCrop:
    def __init__(self, size, padding=0, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        hwc = arr.ndim != 3 or arr.shape[-1] in (1, 3, 4)
        if self.padding:
            p = self.padding
            if hwc:
                pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            else:
                pad = [(0, 0)] * (arr.ndim - 2) + [(p, p), (p, p)]
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2] if hwc else arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        if hwc:
            return Tensor(arr[i:i + th, j:j + tw])
        return Tensor(arr[..., i:i + th, j:j + tw])


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        if np.random.rand() < self.prob:
            arr = arr[:, ::-1].copy()
        return Tensor(arr)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        return Tensor(arr.transpose(self.order))


from . import functional  # noqa: E402
from . import functional as F  # noqa: E402

__all__ += ["functional", "RandomVerticalFlip", "Pad", "ColorJitter",
            "Grayscale", "RandomRotation", "RandomResizedCrop",
            "BrightnessTransform", "ContrastTransform",
            "SaturationTransform", "HueTransform", "RandomErasing"]


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return F.vflip(img)
        return Tensor(np.asarray(img._data)) if isinstance(img, Tensor) \
            else img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BrightnessTransform):
    def __call__(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class SaturationTransform(BrightnessTransform):
    def __call__(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_saturation(img, factor)


class HueTransform:
    def __init__(self, value, keys=None):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(-self.value, self.value)
        return F.adjust_hue(img, factor)


class ColorJitter:
    """Randomly jitter brightness/contrast/saturation/hue, applied in
    random order (upstream semantics)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def __call__(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i](img)
        return img


class Grayscale:
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class RandomResizedCrop:
    """Crop a random area/aspect-ratio patch and resize it (the Inception
    training crop)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            logr = np.random.uniform(np.log(self.ratio[0]),
                                     np.log(self.ratio[1]))
            ar = np.exp(logr)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                patch = arr[i:i + ch, j:j + cw]
                break
        else:  # fallback: center crop to min side
            s = min(h, w)
            i, j = (h - s) // 2, (w - s) // 2
            patch = arr[i:i + s, j:j + s]
        return Resize(self.size, self.interpolation)(patch)


class RandomErasing:
    """Randomly erase a rectangle (Cutout/RandomErasing regularization)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        # same convention as F.erase: Tensor is CHW, ndarray/PIL is HWC,
        # and batched (ndim>=4) arrays are NCHW either way
        chw = (isinstance(img, Tensor) and arr.ndim >= 3) or arr.ndim >= 4
        h, w = (arr.shape[-2:] if chw else arr.shape[:2])
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                return F.erase(img, i, j, eh, ew, self.value, self.inplace)
        return img
