"""Basic vision transforms (python/paddle/vision/transforms parity,
UNVERIFIED) operating on numpy HWC arrays / Tensors."""

from __future__ import annotations

import numpy as np

from ..framework.core import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose", "to_tensor",
           "normalize"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


def to_tensor(pic, data_format="CHW"):
    arr = np.asarray(pic, dtype=np.float32)
    if arr.max() > 1.0:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, pic):
        return to_tensor(pic, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._data)
    else:
        arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        self.mean = mean if isinstance(mean, (list, tuple)) else [mean] * 3
        self.std = std if isinstance(std, (list, tuple)) else [std] * 3
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = img._data if isinstance(img, Tensor) else jnp.asarray(img)
        hwc = arr.ndim == 3 and arr.shape[-1] in (1, 3, 4)
        if hwc:
            out_shape = self.size + (arr.shape[-1],)
        else:
            out_shape = arr.shape[:-2] + self.size
        return Tensor(jax.image.resize(arr, out_shape, "linear"))


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        h, w = arr.shape[:2] if arr.shape[-1] in (1, 3, 4) else \
            arr.shape[-2:]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
            return Tensor(arr[i:i + th, j:j + tw])
        return Tensor(arr[..., i:i + th, j:j + tw])


class RandomCrop:
    def __init__(self, size, padding=0, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        if self.padding:
            p = self.padding
            pad = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return Tensor(arr[i:i + th, j:j + tw])


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        if np.random.rand() < self.prob:
            arr = arr[:, ::-1].copy()
        return Tensor(arr)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img._data if isinstance(img, Tensor) else img)
        return Tensor(arr.transpose(self.order))
