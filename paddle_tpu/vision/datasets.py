"""paddle.vision.datasets — dataset classes.

Reference surface: upstream ``python/paddle/vision/datasets/`` (UNVERIFIED;
see SURVEY.md provenance warning): MNIST/FashionMNIST (idx-ubyte files),
Cifar10/100 (pickled batches), DatasetFolder/ImageFolder (directory trees).
Upstream auto-downloads from bcebos; this environment has zero egress, so
every dataset reads from a local path (``image_path=``/``data_file=`` or
the ``$PADDLE_TPU_HOME`` cache) and raises a clear error when absent —
``backend='generate'`` produces a small deterministic synthetic split so
examples/tests run offline.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset
from ..utils.download import WEIGHTS_HOME

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100",
           "DatasetFolder", "ImageFolder"]

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".ppm", ".webp", ".npy")


def _missing(name, path):
    raise RuntimeError(
        f"{name}: data file {path!r} not found and this environment has no "
        f"network access. Place the file there (or under {WEIGHTS_HOME}), "
        f"or pass backend='generate' for a synthetic offline split.")


class _GeneratedSplit:
    """Deterministic synthetic images: class-dependent gaussian blobs, so a
    small model can actually fit the split (useful for offline examples)."""

    def __init__(self, n, shape, num_classes, seed):
        rng = np.random.RandomState(seed)
        self.labels = rng.randint(0, num_classes, n).astype("int64")
        protos = rng.rand(num_classes, *shape).astype("float32")
        noise = rng.rand(n, *shape).astype("float32") * 0.3
        self.images = (protos[self.labels] * 255 * 0.7 + noise * 255) \
            .astype("uint8")


class MNIST(Dataset):
    """MNIST (idx-ubyte format, same files as upstream paddle's
    ``train-images-idx3-ubyte.gz``)."""

    NAME = "mnist"
    NUM_CLASSES = 10
    IMAGE_SHAPE = (28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        assert mode in ("train", "test")
        self.mode = mode
        self.transform = transform
        self.backend = backend
        if backend == "generate":
            n = 2000 if mode == "train" else 400
            g = _GeneratedSplit(n, self.IMAGE_SHAPE, self.NUM_CLASSES,
                                seed=0 if mode == "train" else 1)
            self.images, self.labels = g.images, g.labels
            return
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            WEIGHTS_HOME, self.NAME, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            WEIGHTS_HOME, self.NAME, f"{prefix}-labels-idx1-ubyte.gz")
        if not os.path.exists(image_path):
            _missing(type(self).__name__, image_path)
        if not os.path.exists(label_path):
            _missing(type(self).__name__, label_path)
        self.images = self._read_idx(image_path, dims=3)
        self.labels = self._read_idx(label_path, dims=1).astype("int64")

    @staticmethod
    def _read_idx(path, dims):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            data = f.read()
        _, _, dt, nd = struct.unpack(">BBBB", data[:4])
        shape = struct.unpack(f">{nd}I", data[4:4 + 4 * nd])
        return np.frombuffer(data[4 + 4 * nd:],
                             dtype=np.uint8).reshape(shape)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 from the python-version tar.gz (``cifar-10-python.tar.gz``,
    the same artifact upstream downloads)."""

    NUM_CLASSES = 10
    _TRAIN_MEMBERS = [f"data_batch_{i}" for i in range(1, 6)]
    _TEST_MEMBERS = ["test_batch"]
    _LABEL_KEY = b"labels"
    _ARCHIVE = "cifar-10-python.tar.gz"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        assert mode in ("train", "test")
        self.mode = mode
        self.transform = transform
        if backend == "generate":
            n = 2000 if mode == "train" else 400
            g = _GeneratedSplit(n, (32, 32, 3), self.NUM_CLASSES,
                                seed=2 if mode == "train" else 3)
            self.images, self.labels = g.images, g.labels
            return
        data_file = data_file or os.path.join(WEIGHTS_HOME, self._ARCHIVE)
        if not os.path.exists(data_file):
            _missing(type(self).__name__, data_file)
        members = self._TRAIN_MEMBERS if mode == "train" \
            else self._TEST_MEMBERS
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tar:
            for m in tar.getmembers():
                base = os.path.basename(m.name)
                if base in members:
                    d = pickle.load(tar.extractfile(m), encoding="bytes")
                    images.append(d[b"data"])
                    labels.extend(d[self._LABEL_KEY])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)  # HWC uint8, paddle convention
        self.labels = np.asarray(labels, dtype="int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class Cifar100(Cifar10):
    NUM_CLASSES = 100
    _TRAIN_MEMBERS = ["train"]
    _TEST_MEMBERS = ["test"]
    _LABEL_KEY = b"fine_labels"
    _ARCHIVE = "cifar-100-python.tar.gz"


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image
    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


class DatasetFolder(Dataset):
    """Directory-per-class image dataset (upstream DatasetFolder):
    root/class_x/xxx.png -> (sample, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or _IMG_EXTS))
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        if not classes:
            raise RuntimeError(f"no class directories found under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (is_valid_file(path) if is_valid_file
                          else fname.lower().endswith(exts))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target


class ImageFolder(Dataset):
    """Flat (unlabeled) image folder: returns [sample] per item, matching
    upstream ImageFolder's list-valued items."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.loader = loader or _default_loader
        self.transform = transform
        exts = tuple(e.lower() for e in (extensions or _IMG_EXTS))
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(exts))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]


class Flowers(Dataset):
    """Oxford 102 Flowers (upstream paddle.vision.datasets.Flowers).
    Cache-only like the rest of this module: reads the upstream
    ``102flowers.tgz``-extracted jpg directory + ``imagelabels.mat`` /
    ``setid.mat`` if present, else ``backend='generate'``."""

    NUM_CLASSES = 102
    IMAGE_SHAPE = (64, 64, 3)

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True,
                 backend=None):
        assert mode in ("train", "valid", "test")
        self.mode = mode
        self.transform = transform
        if backend == "generate":
            n = {"train": 1000, "valid": 200, "test": 400}[mode]
            g = _GeneratedSplit(n, self.IMAGE_SHAPE, self.NUM_CLASSES,
                                seed={"train": 0, "valid": 1,
                                      "test": 2}[mode])
            self.images, self.labels = g.images, g.labels
            return
        import scipy.io as sio
        root = data_file or os.path.join(WEIGHTS_HOME, "flowers")
        label_file = label_file or os.path.join(root, "imagelabels.mat")
        setid_file = setid_file or os.path.join(root, "setid.mat")
        for path in (label_file, setid_file):
            if not os.path.exists(path):
                _missing("Flowers", path)
        labels = sio.loadmat(label_file)["labels"].ravel()
        setid = sio.loadmat(setid_file)
        ids = {"train": setid["trnid"], "valid": setid["valid"],
               "test": setid["tstid"]}[mode].ravel()
        self.ids = ids
        self.root = root
        self.labels = (labels[ids - 1] - 1).astype("int64")
        self.images = None  # lazy jpg loads

    def __len__(self):
        if self.images is not None:
            return len(self.images)
        return len(self.ids)

    def __getitem__(self, idx):
        if self.images is not None:
            img = self.images[idx]
        else:
            from .ops import read_file, decode_jpeg
            path = os.path.join(self.root, "jpg",
                                f"image_{self.ids[idx]:05d}.jpg")
            img = np.asarray(decode_jpeg(read_file(path)).numpy())
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation pairs (upstream
    paddle.vision.datasets.VOC2012): (image, segmentation-mask). Cache-
    only; ``backend='generate'`` yields synthetic pairs offline."""

    IMAGE_SHAPE = (64, 64, 3)

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        assert mode in ("train", "valid", "test")
        self.mode = mode
        self.transform = transform
        if backend == "generate":
            n = {"train": 200, "valid": 50, "test": 50}[mode]
            g = _GeneratedSplit(n, self.IMAGE_SHAPE, 21,
                                seed={"train": 3, "valid": 4,
                                      "test": 5}[mode])
            self.images = g.images
            # synthetic masks: threshold the image mean into 21 classes
            self.masks = (g.images.mean(-1) / 255.0 * 20).astype("int64")
            return
        root = data_file or os.path.join(WEIGHTS_HOME, "voc2012")
        split_file = os.path.join(
            root, "ImageSets", "Segmentation",
            {"train": "train.txt", "valid": "val.txt",
             "test": "val.txt"}[mode])
        if not os.path.exists(split_file):
            _missing("VOC2012", split_file)
        with open(split_file) as fh:
            self.names = [ln.strip() for ln in fh if ln.strip()]
        self.root = root
        self.images = None

    def __len__(self):
        return len(self.images) if self.images is not None \
            else len(self.names)

    def __getitem__(self, idx):
        if self.images is not None:
            img, mask = self.images[idx], self.masks[idx]
        else:
            from .ops import read_file, decode_jpeg
            name = self.names[idx]
            img = np.asarray(decode_jpeg(read_file(os.path.join(
                self.root, "JPEGImages", name + ".jpg"))).numpy())
            from PIL import Image as _Image
            mask = np.asarray(_Image.open(os.path.join(
                self.root, "SegmentationClass", name + ".png")))
        if self.transform is not None:
            img = self.transform(img)
        return img, mask


__all__ += ["Flowers", "VOC2012"]
