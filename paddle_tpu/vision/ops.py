"""paddle.vision.ops — detection/vision operators.

Reference surface: upstream ``python/paddle/vision/ops.py`` (UNVERIFIED —
empty reference mount; see SURVEY.md). The CUDA kernels behind these ops
(nms, roi_align, deform_conv) are re-designed as vectorized XLA programs:
static-shape mask loops instead of dynamic compaction (TPU-friendly), vmap
over ROIs/output pixels instead of per-thread scatter, bilinear sampling as
gather + weighted sum on the MXU/VPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply
from ..ops.common import as_tensor

__all__ = ["nms", "box_iou", "roi_align", "roi_pool", "box_coder",
           "prior_box", "yolo_box", "deform_conv2d", "DeformConv2D",
           "RoIAlign", "RoIPool", "distribute_fpn_proposals"]


def _iou_matrix(boxes_a, boxes_b):
    """Pairwise IoU for [N,4] x [M,4] xyxy boxes."""
    area_a = jnp.maximum(boxes_a[:, 2] - boxes_a[:, 0], 0) * \
        jnp.maximum(boxes_a[:, 3] - boxes_a[:, 1], 0)
    area_b = jnp.maximum(boxes_b[:, 2] - boxes_b[:, 0], 0) * \
        jnp.maximum(boxes_b[:, 3] - boxes_b[:, 1], 0)
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def box_iou(boxes1, boxes2, name=None):
    """Pairwise IoU between two box sets ([N,4], [M,4] in xyxy)."""
    return apply(_iou_matrix, as_tensor(boxes1), as_tensor(boxes2),
                 name="box_iou", differentiable=False)


def _nms_keep_mask(boxes, scores, iou_threshold):
    """Static-shape NMS: returns a keep mask over boxes sorted by nothing —
    the caller pre-sorts. Greedy suppression as a fori_loop over the N
    candidates (N is static, so XLA unrolls/pipelines it)."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    sboxes = boxes[order]
    iou = _iou_matrix(sboxes, sboxes)

    def body(i, keep):
        # keep i only if no earlier kept box overlaps it too much
        sup = jnp.any((iou[:, i] > iou_threshold) & keep
                      & (jnp.arange(n) < i))
        return keep.at[i].set(~sup)

    keep_sorted = jax.lax.fori_loop(0, n, body,
                                    jnp.zeros((n,), jnp.bool_)
                                    .at[0].set(n > 0))
    # scatter back to original order
    keep = jnp.zeros((n,), jnp.bool_).at[order].set(keep_sorted)
    return keep, order


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy non-maximum suppression (paddle.vision.ops.nms).

    Returns kept box indices, highest score first. With ``category_idxs``
    the suppression is per-category (boxes of different categories never
    suppress each other), implemented by offsetting boxes per category so
    one fused NMS pass handles all categories (the standard batched-NMS
    trick — no per-category loop on device).
    """
    b = as_tensor(boxes).jax().astype(jnp.float32)
    n = b.shape[0]
    s = (as_tensor(scores).jax().astype(jnp.float32)
         if scores is not None else jnp.arange(n, 0, -1, dtype=jnp.float32))
    if category_idxs is not None:
        cat = as_tensor(category_idxs).jax()
        span = jnp.max(b) - jnp.min(b) + 1.0
        b = b + (cat.astype(jnp.float32) * span)[:, None]
    keep, order = _nms_keep_mask(b, s, float(iou_threshold))
    kept_sorted = order[keep[order]]  # original indices, score-descending
    if top_k is not None:
        kept_sorted = kept_sorted[:top_k]
    return Tensor(kept_sorted.astype(jnp.int64))


def _bilinear_sample(feat, y, x):
    """Sample feat [C,H,W] at fractional (y, x) grids of any shape."""
    H, W = feat.shape[-2], feat.shape[-1]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1, x1 = y0 + 1, x0 + 1
    wy1, wx1 = y - y0, x - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def gather(yy, xx):
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        return feat[:, yi, xi]  # [C, ...grid]

    valid = ((y > -1.0) & (y < H) & (x > -1.0) & (x < W))
    out = (gather(y0, x0) * (wy0 * wx0) + gather(y0, x1) * (wy0 * wx1)
           + gather(y1, x0) * (wy1 * wx0) + gather(y1, x1) * (wy1 * wx1))
    return out * valid


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (Mask R-CNN): average of bilinear samples on a regular grid
    inside each ROI bin. vmap over ROIs; each ROI's sampling grid is one
    vectorized gather."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 2 if sampling_ratio <= 0 else int(sampling_ratio)

    def fn(feat, rois, rois_num):
        # rois: [R, 4] xyxy in input coordinates; rois_num: [B]
        offset = 0.5 if aligned else 0.0
        # map each roi to its batch image via the boxes_num prefix sum
        batch_idx = jnp.searchsorted(jnp.cumsum(rois_num),
                                     jnp.arange(rois.shape[0]), side="right")

        def one(roi, bi):
            x1, y1, x2, y2 = (roi * spatial_scale) - offset
            rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
            rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
            bin_h, bin_w = rh / ph, rw / pw
            # sample grid [ph*ratio, pw*ratio]
            gy = y1 + (jnp.arange(ph * ratio) + 0.5) * (bin_h / ratio)
            gx = x1 + (jnp.arange(pw * ratio) + 0.5) * (bin_w / ratio)
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            samples = _bilinear_sample(feat[bi], yy, xx)  # [C, phr, pwr]
            C = samples.shape[0]
            samples = samples.reshape(C, ph, ratio, pw, ratio)
            return samples.mean(axis=(2, 4))  # [C, ph, pw]

        return jax.vmap(one)(rois, batch_idx)

    return apply(fn, as_tensor(x), as_tensor(boxes), as_tensor(boxes_num),
                 name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (Fast R-CNN): max over quantized bins. Implemented as a dense
    max over a fine sampling grid per bin (quantization-free on TPU — exact
    argmax-free max pooling via gather grid)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 4

    def fn(feat, rois, rois_num):
        batch_idx = jnp.searchsorted(jnp.cumsum(rois_num),
                                     jnp.arange(rois.shape[0]), side="right")

        def one(roi, bi):
            x1, y1, x2, y2 = roi * spatial_scale
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            gy = y1 + (jnp.arange(ph * ratio) + 0.5) * (rh / (ph * ratio))
            gx = x1 + (jnp.arange(pw * ratio) + 0.5) * (rw / (pw * ratio))
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            samples = _bilinear_sample(feat[bi], yy, xx)
            C = samples.shape[0]
            samples = samples.reshape(C, ph, ratio, pw, ratio)
            return samples.max(axis=(2, 4))

        return jax.vmap(one)(rois, batch_idx)

    return apply(fn, as_tensor(x), as_tensor(boxes), as_tensor(boxes_num),
                 name="roi_pool")


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (SSD-style)."""
    def fn(prior, pvar, target):
        norm = 0.0 if box_normalized else 1.0
        pw = prior[:, 2] - prior[:, 0] + norm
        ph = prior[:, 3] - prior[:, 1] + norm
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = target[:, 2] - target[:, 0] + norm
            th = target[:, 3] - target[:, 1] + norm
            tcx = target[:, 0] + tw * 0.5
            tcy = target[:, 1] + th * 0.5
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(tw[:, None] / pw[None, :]),
                jnp.log(th[:, None] / ph[None, :]),
            ], axis=-1)
            if pvar is not None:
                # per-prior [P,4] or a single [4] variance vector
                out = out / (pvar[None, :, :] if pvar.ndim == 2 else pvar)
            return out
        # decode_center_size: target [N, P, 4] deltas
        t = target
        if axis == 1:
            pcx_, pcy_, pw_, ph_ = (v[None, :] for v in (pcx, pcy, pw, ph))
        else:
            pcx_, pcy_, pw_, ph_ = (v[:, None] if t.ndim == 3 else v
                                    for v in (pcx, pcy, pw, ph))
        d = t * pvar if pvar is not None else t
        cx = d[..., 0] * pw_ + pcx_
        cy = d[..., 1] * ph_ + pcy_
        w = jnp.exp(d[..., 2]) * pw_
        h = jnp.exp(d[..., 3]) * ph_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], -1)

    prior = as_tensor(prior_box)
    target = as_tensor(target_box)
    if prior_box_var is None:
        return apply(lambda p, t: fn(p, None, t), prior, target,
                     name="box_coder")
    pvar = as_tensor(jnp.asarray(prior_box_var, jnp.float32)
                     if isinstance(prior_box_var, (list, tuple))
                     else prior_box_var)
    return apply(fn, prior, pvar, target, name="box_coder")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes for one feature map."""
    feat = as_tensor(input).jax()
    img = as_tensor(image).jax()
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)

    whs = []
    for ms in min_sizes:
        for ar in ars:
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        if max_sizes:
            for mx in max_sizes:
                s = (ms * mx) ** 0.5
                whs.append((s, s))
    whs = jnp.asarray(whs, jnp.float32)  # [A, 2]

    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    cyy, cxx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([cxx, cyy], -1)[..., None, :]  # [fh, fw, 1, 2]
    half = whs[None, None] * 0.5
    mins = (centers - half) / jnp.asarray([iw, ih], jnp.float32)
    maxs = (centers + half) / jnp.asarray([iw, ih], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], -1)  # [fh, fw, A, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return Tensor(boxes), Tensor(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head output into boxes + scores."""
    def fn(feat, imgs):
        b, _, h, w = feat.shape
        na = len(anchors) // 2
        anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
        iou_pred = None
        if iou_aware:
            # iou-aware head layout: [na * iou, na * (5 + cls)] channels
            iou_pred = feat[:, :na].reshape(b, na, h, w)
            feat = feat[:, na:]
        pred = feat.reshape(b, na, 5 + class_num, h, w)
        gx, gy = jnp.meshgrid(jnp.arange(w, dtype=jnp.float32),
                              jnp.arange(h, dtype=jnp.float32),
                              indexing="xy")
        sx = jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y \
            - (scale_x_y - 1) / 2 + gx
        sy = jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y \
            - (scale_x_y - 1) / 2 + gy
        bw = jnp.exp(pred[:, :, 2]) * anc[None, :, 0, None, None] / \
            (downsample_ratio * w)
        bh = jnp.exp(pred[:, :, 3]) * anc[None, :, 1, None, None] / \
            (downsample_ratio * h)
        cx, cy = sx / w, sy / h
        conf = jax.nn.sigmoid(pred[:, :, 4])
        if iou_pred is not None:
            iou = jax.nn.sigmoid(iou_pred)
            conf = conf ** (1.0 - iou_aware_factor) * \
                iou ** iou_aware_factor
        probs = jax.nn.sigmoid(pred[:, :, 5:]) * conf[:, :, None]
        mask = conf > conf_thresh
        ih = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        iw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * iw
        y1 = (cy - bh / 2) * ih
        x2 = (cx + bw / 2) * iw
        y2 = (cy + bh / 2) * ih
        if clip_bbox:
            x1 = jnp.clip(x1, 0, iw - 1)
            y1 = jnp.clip(y1, 0, ih - 1)
            x2 = jnp.clip(x2, 0, iw - 1)
            y2 = jnp.clip(y2, 0, ih - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1) * mask[..., None]
        scores = probs * mask[:, :, None]
        boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(b, -1, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(
            b, -1, class_num)
        return boxes, scores

    return apply(fn, as_tensor(x), as_tensor(img_size), n_outputs=2,
                 name="yolo_box", differentiable=False)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign ROIs to FPN levels by scale (eager helper — returns per-level
    ROI tensors + restore index)."""
    import numpy as np
    rois = np.asarray(as_tensor(fpn_rois).numpy())
    off = 1.0 if pixel_offset else 0.0
    w = np.maximum(rois[:, 2] - rois[:, 0] + off, 0)
    h = np.maximum(rois[:, 3] - rois[:, 1] + off, 0)
    scale = np.sqrt(w * h)
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs, nums, order = [], [], []
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        nums.append(Tensor(jnp.asarray([len(idx)], dtype=jnp.int32)))
        order.append(idx)
    restore = np.argsort(np.concatenate(order)) if order else np.zeros(0)
    return outs, Tensor(jnp.asarray(restore.astype(np.int32))), nums


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (DCN): bilinear-sample the input at
    offset-shifted taps, then a dense matmul with the kernel — the gather
    feeds the MXU instead of a scatter-heavy CUDA kernel."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def fn(xa, off, w, *rest):
        mask_a = None
        bias_a = None
        rest = list(rest)
        if mask is not None:
            mask_a = rest.pop(0)
        if bias is not None:
            bias_a = rest.pop(0)
        B, C, H, W = xa.shape
        Co, Cg, kh, kw = w.shape
        oh = (H + 2 * padding[0] - dilation[0] * (kh - 1) - 1) \
            // stride[0] + 1
        ow = (W + 2 * padding[1] - dilation[1] * (kw - 1) - 1) \
            // stride[1] + 1
        xp = jnp.pad(xa, ((0, 0), (0, 0), (padding[0], padding[0]),
                          (padding[1], padding[1])))
        # base sampling positions for each output pixel and tap
        oy = jnp.arange(oh) * stride[0]
        ox = jnp.arange(ow) * stride[1]
        ky = jnp.arange(kh) * dilation[0]
        kx = jnp.arange(kw) * dilation[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        # offsets: [B, 2*dg*kh*kw, oh, ow] -> y/x per tap
        off = off.reshape(B, deformable_groups, kh * kw, 2, oh, ow)
        off_y = off[:, :, :, 0].reshape(B, deformable_groups, kh, kw, oh, ow)
        off_x = off[:, :, :, 1].reshape(B, deformable_groups, kh, kw, oh, ow)

        cpg = C // deformable_groups  # channels per deformable group
        base_yk = base_y.transpose(2, 3, 0, 1)  # [kh, kw, oh, ow] broadcast
        base_xk = base_x.transpose(2, 3, 0, 1)
        msk_all = (mask_a.reshape(B, deformable_groups, kh, kw, oh, ow)
                   if mask_a is not None else
                   jnp.ones((B, deformable_groups, kh, kw, oh, ow),
                            xa.dtype))

        def sample_group(img, offy, offx, msk):
            # img [cpg, Hp, Wp]; offy/offx/msk [kh, kw, oh, ow]
            s = _bilinear_sample(img, base_yk + offy, base_xk + offx)
            return s * msk  # [cpg, kh, kw, oh, ow]

        def one_batch(img, offy, offx, msk):
            img_g = img.reshape(deformable_groups, cpg, *img.shape[1:])
            cols = jax.vmap(sample_group)(img_g, offy, offx, msk)
            return cols.reshape(C, kh, kw, oh, ow)

        cols = jax.vmap(one_batch)(xp, off_y, off_x, msk_all)
        # cols: [B, C, kh, kw, oh, ow] -> grouped matmul with weight
        cpgrp = C // groups
        cols = cols.reshape(B, groups, cpgrp * kh * kw, oh * ow)
        wg = w.reshape(groups, Co // groups, Cg * kh * kw)
        out = jnp.einsum("bgkp,gok->bgop", cols, wg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(B, Co, oh, ow).astype(xa.dtype)
        if bias_a is not None:
            out = out + bias_a[None, :, None, None]
        return out

    args = [as_tensor(x), as_tensor(offset), as_tensor(weight)]
    if mask is not None:
        args.append(as_tensor(mask))
    if bias is not None:
        args.append(as_tensor(bias))
    return apply(fn, *args, name="deform_conv2d")


class DeformConv2D:
    """Layer wrapper over deform_conv2d (paddle.vision.ops.DeformConv2D)."""

    def __new__(cls, in_channels, out_channels, kernel_size, stride=1,
                padding=0, dilation=1, deformable_groups=1, groups=1,
                weight_attr=None, bias_attr=None):
        from ..nn.layer.layers import Layer
        from ..nn import initializer as I

        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)

        class _DCN(Layer):
            def __init__(self):
                super().__init__()
                fan_in = in_channels * ks[0] * ks[1]
                bound = 1.0 / (fan_in ** 0.5)
                self.weight = self.create_parameter(
                    [out_channels, in_channels // groups, *ks],
                    attr=weight_attr,
                    default_initializer=I.Uniform(-bound, bound))
                self.bias = None if bias_attr is False else \
                    self.create_parameter(
                        [out_channels], attr=bias_attr, is_bias=True,
                        default_initializer=I.Uniform(-bound, bound))

            def forward(self, x, offset, mask=None):
                return deform_conv2d(
                    x, offset, self.weight, self.bias, stride, padding,
                    dilation, deformable_groups, groups, mask)

        return _DCN()


class RoIAlign:
    def __new__(cls, output_size, spatial_scale=1.0):
        from ..nn.layer.layers import Layer

        class _R(Layer):
            def forward(self, x, boxes, boxes_num):
                return roi_align(x, boxes, boxes_num, output_size,
                                 spatial_scale)

        return _R()


class RoIPool:
    def __new__(cls, output_size, spatial_scale=1.0):
        from ..nn.layer.layers import Layer

        class _R(Layer):
            def forward(self, x, boxes, boxes_num):
                return roi_pool(x, boxes, boxes_num, output_size,
                                spatial_scale)

        return _R()


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): soft decay of each box's score by its IoU with
    higher-scored same-class boxes — one dense IoU matrix instead of a
    sequential suppression loop (the TPU-friendly formulation).

    bboxes: [N, M, 4]; scores: [N, C, M]. Returns (out [K, 6] rows of
    (label, score, x1, y1, x2, y2), [index], rois_num)."""
    bt, st = as_tensor(bboxes), as_tensor(scores)
    n, c, m = st.shape
    top = min(int(nms_top_k), int(m)) if nms_top_k > 0 else int(m)

    def one_image(bx, sc):
        # per class: take top-k by score, decay by the SOLOv2 rule
        # decay_j = min_{i<j} f(iou_ij) / f(comp_i),
        # comp_i = max_{k<i} iou_ki, f linear (1-x) or gaussian
        def one_class(cls_scores):
            v, idx = jax.lax.top_k(cls_scores, top)
            bsel = bx[idx]
            iou = _iou_matrix(bsel, bsel)
            upper = jnp.triu(iou, k=1)           # iou_ij for i < j
            comp = jnp.max(upper, axis=0)        # comp[i]
            valid = jnp.triu(jnp.ones_like(upper, bool), k=1)
            if use_gaussian:
                dm = jnp.exp(-(upper ** 2 - comp[:, None] ** 2)
                             / gaussian_sigma)
            else:
                dm = (1 - upper) / jnp.maximum(1 - comp[:, None], 1e-9)
            d = jnp.min(jnp.where(valid, dm, 1.0), axis=0)
            return v * d, idx

        dec, idxs = jax.vmap(one_class)(sc)       # [C, top]
        return dec, idxs

    dec_t, idx_t = apply(lambda b, s: jax.vmap(one_image)(b, s),
                         bt, st, n_outputs=2, name="matrix_nms",
                         differentiable=False)
    import numpy as np
    dec = np.asarray(dec_t._data)                 # [N, C, top]
    idxs = np.asarray(idx_t._data)
    bx_np = np.asarray(bt._data)
    rows, flat_index, rois_num = [], [], []
    for i in range(n):
        cand = []
        for cls in range(c):
            if cls == background_label and c > 1:
                continue
            for j in range(dec.shape[2]):
                s = float(dec[i, cls, j])
                if s >= float(post_threshold) and s >= float(
                        score_threshold):
                    bi = int(idxs[i, cls, j])
                    cand.append((s, cls, bi))
        cand.sort(reverse=True)
        if keep_top_k > 0:
            cand = cand[:int(keep_top_k)]
        rois_num.append(len(cand))
        for s, cls, bi in cand:
            rows.append([cls, s] + bx_np[i, bi].tolist())
            flat_index.append(i * m + bi)
    out = Tensor(jnp.asarray(np.asarray(rows, np.float32).reshape(-1, 6)))
    num = Tensor(jnp.asarray(np.asarray(rois_num, np.int32)))
    if return_index:
        idx_out = Tensor(jnp.asarray(np.asarray(flat_index, np.int64)))
        return (out, idx_out, num) if return_rois_num else (out, idx_out)
    return (out, num) if return_rois_num else out


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (R-FCN): channel block (i, j) is
    average-pooled over spatial bin (i, j) of each RoI."""
    xt, bt = as_tensor(x), as_tensor(boxes)
    if isinstance(output_size, int):
        ph = pw = int(output_size)
    else:
        ph, pw = output_size
    c = xt.shape[1]
    assert c % (ph * pw) == 0, (
        f"psroi_pool: channels {c} not divisible by output bins "
        f"{ph * pw}")
    co = c // (ph * pw)
    # RoI -> image mapping from boxes_num (host-concrete, like the
    # reference's rois_num contract)
    import numpy as _np
    bn = _np.asarray(as_tensor(boxes_num)._data).astype(_np.int64)
    roi_img = _np.repeat(_np.arange(len(bn)), bn).astype(_np.int32)
    roi_img_t = as_tensor(roi_img)

    def fn(feat, rois, img_idx):
        hh, ww = feat.shape[2], feat.shape[3]

        def one(roi, bi):
            fimg = feat[bi]                       # [C, H, W]
            x1, y1, x2, y2 = [roi[k] * spatial_scale for k in range(4)]
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            ys = jnp.linspace(0.0, 1.0, ph + 1) * rh + y1
            xs = jnp.linspace(0.0, 1.0, pw + 1) * rw + x1
            out = jnp.zeros((co, ph, pw), feat.dtype)
            # average over each bin via a weighted mask (dense, static)
            gy = jnp.arange(hh, dtype=jnp.float32)
            gx = jnp.arange(ww, dtype=jnp.float32)
            for i in range(ph):
                my = ((gy >= ys[i]) & (gy < jnp.maximum(
                    ys[i + 1], ys[i] + 1))).astype(feat.dtype)
                for j in range(pw):
                    mx_ = ((gx >= xs[j]) & (gx < jnp.maximum(
                        xs[j + 1], xs[j] + 1))).astype(feat.dtype)
                    mask = my[:, None] * mx_[None, :]
                    cnt = jnp.maximum(mask.sum(), 1.0)
                    blk = fimg[(i * pw + j) * co:(i * pw + j + 1) * co]
                    val = (blk * mask[None]).sum((-2, -1)) / cnt
                    out = out.at[:, i, j].set(val)
            return out

        return jax.vmap(one)(rois, img_idx)

    return apply(fn, xt, bt, roi_img_t, name="psroi_pool")


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True,
                       name=None):
    """RPN proposal generation: decode anchor deltas -> clip -> filter by
    size -> top-k by score -> NMS (host-composed from the dense ops)."""
    import numpy as np
    sc = np.asarray(as_tensor(scores)._data)        # [N, A, H, W]
    bd = np.asarray(as_tensor(bbox_deltas)._data)   # [N, 4A, H, W]
    an = np.asarray(as_tensor(anchors)._data).reshape(-1, 4)
    va = np.asarray(as_tensor(variances)._data).reshape(-1, 4)
    im = np.asarray(as_tensor(img_size)._data)
    n = sc.shape[0]
    off = 1.0 if pixel_offset else 0.0   # paddle-1.x box convention
    out_rois, out_num, out_scores = [], [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)
        d = bd[i].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        acx = an[:, 0] + aw / 2
        acy = an[:, 1] + ah / 2
        cx = va[:, 0] * d[:, 0] * aw + acx
        cy = va[:, 1] * d[:, 1] * ah + acy
        w = np.exp(np.minimum(va[:, 2] * d[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(va[:, 3] * d[:, 3], 10.0)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2 - off,
                          cy + h / 2 - off], axis=1)
        hmax, wmax = float(im[i, 0]), float(im[i, 1])
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, wmax - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, hmax - off)
        keep = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[keep], s[keep]
        order = np.argsort(-s)[:int(pre_nms_top_n)]
        boxes, s = boxes[order], s[order]
        if len(boxes):
            kept = np.asarray(nms(
                Tensor(jnp.asarray(boxes.astype(np.float32))),
                iou_threshold=float(nms_thresh),
                scores=Tensor(jnp.asarray(s.astype(np.float32))),
                top_k=int(post_nms_top_n)).numpy())
        else:
            kept = np.zeros((0,), np.int64)
        sel = boxes[kept] if len(kept) else np.zeros((0, 4), np.float32)
        out_rois.append(sel.astype(np.float32))
        out_scores.append(s[kept].astype(np.float32) if len(kept)
                          else np.zeros((0,), np.float32))
        out_num.append(len(sel))
    rois = Tensor(jnp.asarray(np.concatenate(out_rois, 0)
                              if out_rois else np.zeros((0, 4),
                                                        np.float32)))
    rscores = Tensor(jnp.asarray(np.concatenate(out_scores, 0)))
    num = Tensor(jnp.asarray(np.asarray(out_num, np.int32)))
    if return_rois_num:
        return rois, rscores, num
    return rois, rscores


def read_file(filename, name=None):
    """Read raw bytes as a uint8 tensor (paddle.vision.ops.read_file)."""
    import numpy as np
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to [C, H, W] uint8 (via PIL — the
    reference uses nvjpeg; host decode is the TPU-side equivalent)."""
    import io

    import numpy as np
    from PIL import Image

    raw = bytes(np.asarray(as_tensor(x)._data).astype(np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode in ("rgb", "RGB"):
        img = img.convert("RGB")
    elif mode in ("gray", "grayscale", "L"):
        img = img.convert("L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


__all__ += ["matrix_nms", "psroi_pool", "generate_proposals", "read_file",
            "decode_jpeg"]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss for one detection head (paddle.vision.ops.yolo_loss).

    x: [N, mask*(5+C), H, W] raw head output; gt_box: [N, B, 4] boxes as
    (cx, cy, w, h) normalized to the input image; gt_label: [N, B] int;
    anchors: flat (w0, h0, w1, h1, ...) in input pixels; anchor_mask
    selects this head's anchors. Returns per-image loss [N].

    Loss form follows the reference op: sigmoid cross-entropy for the
    x/y offsets and objectness/class terms, L1 for w/h, coordinate terms
    weighted by gt_score * (2 - w*h), label smoothing with
    min(1/C, 1/40), scale_x_y applied to the decode and inverted on the
    x/y targets. TPU formulation: per-box work is only target SCATTERS;
    every loss term is one dense masked reduction (no per-box loss
    subgraphs)."""
    xt = as_tensor(x)
    gb, gl = as_tensor(gt_box), as_tensor(gt_label)
    gs = as_tensor(gt_score) if gt_score is not None else None
    am = [int(a) for a in anchor_mask]
    an_all = [float(a) for a in anchors]
    an_pairs = [(an_all[2 * i], an_all[2 * i + 1])
                for i in range(len(an_all) // 2)]
    mask_anchors = [an_pairs[i] for i in am]
    m = len(am)
    c = int(class_num)
    sw = min(1.0 / c, 1.0 / 40.0) if use_label_smooth else 0.0
    sxy = float(scale_x_y)

    def fn(pred, boxes, labels, *rest):
        n, _, hh, ww = pred.shape
        score = rest[0] if rest else jnp.ones(labels.shape, jnp.float32)
        in_w = ww * downsample_ratio
        in_h = hh * downsample_ratio
        p = pred.reshape(n, m, 5 + c, hh, ww)
        tx, ty = p[:, :, 0], p[:, :, 1]
        tw, th = p[:, :, 2], p[:, :, 3]
        tobj = p[:, :, 4]
        tcls = p[:, :, 5:]
        # scaled-xy decode (PP-YOLO/YOLOv4): sigmoid(t)*s - (s-1)/2
        sx = jax.nn.sigmoid(tx) * sxy - (sxy - 1.0) / 2.0
        sy = jax.nn.sigmoid(ty) * sxy - (sxy - 1.0) / 2.0
        gx = (jnp.arange(ww) + 0.0)[None, None, None, :]
        gy = (jnp.arange(hh) + 0.0)[None, None, :, None]
        aw = jnp.asarray([a[0] for a in mask_anchors])[None, :, None, None]
        ah = jnp.asarray([a[1] for a in mask_anchors])[None, :, None, None]
        pcx = (gx + sx) / ww
        pcy = (gy + sy) / hh
        pw = jnp.exp(jnp.clip(tw, -10, 10)) * aw / in_w
        ph = jnp.exp(jnp.clip(th, -10, 10)) * ah / in_h

        bcx, bcy = boxes[..., 0], boxes[..., 1]
        bw, bh = boxes[..., 2], boxes[..., 3]
        valid = (bw > 0) & (bh > 0)

        def iou_cw(cx1, cy1, w1, h1, cx2, cy2, w2, h2):
            l1, r1 = cx1 - w1 / 2, cx1 + w1 / 2
            t1, b1 = cy1 - h1 / 2, cy1 + h1 / 2
            l2, r2 = cx2 - w2 / 2, cx2 + w2 / 2
            t2, b2 = cy2 - h2 / 2, cy2 + h2 / 2
            iw = jnp.maximum(jnp.minimum(r1, r2) - jnp.maximum(l1, l2), 0)
            ih = jnp.maximum(jnp.minimum(b1, b2) - jnp.maximum(t1, t2), 0)
            inter = iw * ih
            return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-9)

        # ignore mask: best IoU of each prediction with any gt (one
        # vectorized [N, B, m, H, W]-free pass via a scan over B)
        nb = boxes.shape[1]

        def best_iou_body(best, bi):
            i = iou_cw(pcx, pcy, pw, ph,
                       bcx[:, bi, None, None, None],
                       bcy[:, bi, None, None, None],
                       bw[:, bi, None, None, None],
                       bh[:, bi, None, None, None])
            return jnp.maximum(best, i * valid[:, bi, None, None, None]), \
                None
        best, _ = jax.lax.scan(best_iou_body,
                               jnp.zeros((n, m, hh, ww)),
                               jnp.arange(nb))
        noobj_mask = (best < ignore_thresh).astype(jnp.float32)

        # ---- per-box target SCATTERS (the only per-box work) ----------
        zero = jnp.zeros((n, m, hh, ww))
        tgt_obj = zero          # gt_score at responsible cells
        tgt_w = zero            # coord weight: score * (2 - w*h)
        tgt_tx = zero
        tgt_ty = zero
        tgt_tw = zero
        tgt_th = zero
        tgt_cls = jnp.zeros((n, m, c, hh, ww))
        aw_m = jnp.asarray([a[0] for a in mask_anchors])
        ah_m = jnp.asarray([a[1] for a in mask_anchors])
        bidx = jnp.arange(n)
        for bi in range(nb):
            v = valid[:, bi].astype(jnp.float32)
            cx, cy = bcx[:, bi], bcy[:, bi]
            w_, h_ = bw[:, bi], bh[:, bi]
            gi = jnp.clip((cx * ww).astype(jnp.int32), 0, ww - 1)
            gj = jnp.clip((cy * hh).astype(jnp.int32), 0, hh - 1)
            ious_a = jnp.stack([
                iou_cw(0.0, 0.0, w_ * in_w, h_ * in_h, 0.0, 0.0,
                       a[0], a[1]) for a in an_pairs], -1)
            best_a = jnp.argmax(ious_a, -1)                   # [N]
            # responsible only if the best anchor belongs to this head
            mi = jnp.zeros((n,), jnp.int32)
            resp = jnp.zeros((n,))
            for local, a_idx in enumerate(am):
                hit = (best_a == a_idx)
                mi = jnp.where(hit, local, mi)
                resp = jnp.maximum(resp, hit.astype(jnp.float32))
            resp = resp * v
            sc_b = score[:, bi] * resp
            # x/y targets inverse of the scaled decode, clipped into (0,1)
            txt = cx * ww - jnp.floor(cx * ww)
            tyt = cy * hh - jnp.floor(cy * hh)
            if sxy != 1.0:
                txt = jnp.clip((txt + (sxy - 1.0) / 2.0) / sxy,
                               1e-4, 1 - 1e-4)
                tyt = jnp.clip((tyt + (sxy - 1.0) / 2.0) / sxy,
                               1e-4, 1 - 1e-4)
            twt = jnp.log(jnp.maximum(w_ * in_w / aw_m[mi], 1e-9))
            tht = jnp.log(jnp.maximum(h_ * in_h / ah_m[mi], 1e-9))
            coord_w = sc_b * (2.0 - w_ * h_)
            tgt_obj = tgt_obj.at[bidx, mi, gj, gi].max(sc_b)
            tgt_w = tgt_w.at[bidx, mi, gj, gi].max(coord_w)
            tgt_tx = tgt_tx.at[bidx, mi, gj, gi].set(
                jnp.where(resp > 0, txt,
                          tgt_tx[bidx, mi, gj, gi]))
            tgt_ty = tgt_ty.at[bidx, mi, gj, gi].set(
                jnp.where(resp > 0, tyt,
                          tgt_ty[bidx, mi, gj, gi]))
            tgt_tw = tgt_tw.at[bidx, mi, gj, gi].set(
                jnp.where(resp > 0, twt,
                          tgt_tw[bidx, mi, gj, gi]))
            tgt_th = tgt_th.at[bidx, mi, gj, gi].set(
                jnp.where(resp > 0, tht,
                          tgt_th[bidx, mi, gj, gi]))
            onehot = jax.nn.one_hot(labels[:, bi], c)
            tgt_cls = tgt_cls.at[bidx, mi, :, gj, gi].set(
                jnp.where((resp > 0)[:, None], onehot,
                          tgt_cls[bidx, mi, :, gj, gi]))

        pos = (tgt_obj > 0).astype(jnp.float32)

        def sce(logit, target):
            return -(target * jax.nn.log_sigmoid(logit)
                     + (1 - target) * jax.nn.log_sigmoid(-logit))

        # ---- dense loss terms (computed ONCE) -------------------------
        # x/y: sigmoid cross-entropy on raw logits; w/h: L1 — the
        # reference op's loss form, weighted by score*(2-w*h)
        lxy = tgt_w * (sce(tx, tgt_tx) + sce(ty, tgt_ty))
        lwh = tgt_w * (jnp.abs(tw - tgt_tw) + jnp.abs(th - tgt_th))
        # objectness: positive BCE weighted by gt_score; background BCE
        # only where best IoU stays under ignore_thresh
        lobj = (tgt_obj * sce(tobj, jnp.ones_like(tobj))
                + (1 - pos) * noobj_mask
                * sce(tobj, jnp.zeros_like(tobj)))
        # class: smoothed targets pos=1-sw, neg=sw at responsible cells
        cls_target = tgt_cls * (1 - 2 * sw) + sw
        lcls = pos[:, :, None] * sce(tcls, cls_target)
        return (jnp.sum(lxy + lwh, axis=(1, 2, 3))
                + jnp.sum(lobj, axis=(1, 2, 3))
                + jnp.sum(lcls, axis=(1, 2, 3, 4)))

    args = [xt, gb, gl]
    if gs is not None:
        args.append(gs)
    return apply(fn, *args, name="yolo_loss")


__all__ += ["yolo_loss"]


class PSRoIPool:
    """Position-sensitive RoI pooling layer over ``psroi_pool``
    (paddle.vision.ops.PSRoIPool parity)."""

    def __new__(cls, output_size, spatial_scale=1.0):
        from ..nn.layer.layers import Layer

        class _P(Layer):
            def forward(self, x, boxes, boxes_num):
                return psroi_pool(x, boxes, boxes_num, output_size,
                                  spatial_scale)

        return _P()


__all__ += ["PSRoIPool"]
