"""Vision models (python/paddle/vision/models/ parity, UNVERIFIED):
ResNet/ResNeXt/WideResNet, VGG, AlexNet, MobileNetV1/V2/V3, SqueezeNet,
ShuffleNetV2, DenseNet, GoogLeNet, LeNet — conv-net coverage for the
framework (NCHW, BatchNorm, pooling, the full CNN path on the MXU)."""

from __future__ import annotations

from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Flatten, Dropout
from ..nn.layer.container import Sequential
from ..nn.layer.conv import Conv2D
from ..nn.layer.norm import BatchNorm2D
from ..nn.layer.activation import (ReLU, ReLU6, Hardswish, Hardsigmoid,
                                   Sigmoid)
from ..nn.layer.pooling import (MaxPool2D, AvgPool2D, AdaptiveAvgPool2D)
from ..nn import functional as F

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
           "resnet152", "resnext50_32x4d", "resnext101_32x4d",
           "wide_resnet50_2", "wide_resnet101_2", "LeNet", "BasicBlock",
           "BottleneckBlock", "AlexNet", "alexnet", "VGG", "vgg11", "vgg13",
           "vgg16", "vgg19", "MobileNetV1", "mobilenet_v1", "MobileNetV2",
           "mobilenet_v2", "MobileNetV3Small", "MobileNetV3Large",
           "mobilenet_v3_small", "mobilenet_v3_large", "SqueezeNet",
           "squeezenet1_0", "squeezenet1_1", "ShuffleNetV2",
           "shufflenet_v2_x1_0", "DenseNet", "densenet121", "GoogLeNet",
           "googlenet"]


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(planes)
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = BatchNorm2D(planes)
        self.downsample = downsample
        self.relu = ReLU()

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False)
        self.bn2 = BatchNorm2D(width)
        self.conv3 = Conv2D(width, planes * self.expansion, 1,
                            bias_attr=False)
        self.bn3 = BatchNorm2D(planes * self.expansion)
        self.downsample = downsample
        self.relu = ReLU()

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        self.groups = groups
        self.base_width = width
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3],
                     50: [3, 4, 6, 3], 101: [3, 4, 23, 3],
                     152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.inplanes = 64
        self.conv1 = Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(self.inplanes)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], 2)
        self.layer3 = self._make_layer(block, 256, layers[2], 2)
        self.layer4 = self._make_layer(block, 512, layers[3], 2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1,
                       stride=stride, bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        extra = ({"groups": self.groups, "base_width": self.base_width}
                 if block is BottleneckBlock else {})
        layers = [block(self.inplanes, planes, stride, downsample, **extra)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **extra))
        return Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ..ops.manipulation import flatten
            x = flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, **kwargs)


class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1), ReLU(),
            MaxPool2D(2, 2))
        self.fc = Sequential(
            Linear(400, 120), Linear(120, 84), Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        from ..ops.manipulation import flatten
        return self.fc(flatten(x, 1))


def resnext50_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=4, groups=32, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=4, groups=32, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=128, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=128, **kwargs)


def _flatten1(x):
    from ..ops.manipulation import flatten
    return flatten(x, 1)


class AlexNet(Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(3, stride=2))
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.5), Linear(256 * 36, 4096), ReLU(),
                Dropout(0.5), Linear(4096, 4096), ReLU(),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(_flatten1(x))
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


_VGG_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 49, 4096), ReLU(), Dropout(0.5),
                Linear(4096, 4096), ReLU(), Dropout(0.5),
                Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(_flatten1(x))
        return x


def _vgg_features(cfg, batch_norm):
    layers, c_in = [], 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(2, 2))
        else:
            layers.append(Conv2D(c_in, v, 3, padding=1))
            if batch_norm:
                layers.append(BatchNorm2D(v))
            layers.append(ReLU())
            c_in = v
    return Sequential(*layers)


def _vgg(depth, batch_norm=False, **kwargs):
    return VGG(_vgg_features(_VGG_CFGS[depth], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(11, batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(13, batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(16, batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(19, batch_norm, **kwargs)


def _conv_bn(c_in, c_out, k, stride=1, padding=0, groups=1, act=ReLU):
    layers = [Conv2D(c_in, c_out, k, stride=stride, padding=padding,
                     groups=groups, bias_attr=False), BatchNorm2D(c_out)]
    if act is not None:
        layers.append(act())
    return Sequential(*layers)


class MobileNetV1(Layer):
    """Depthwise-separable conv net. Depthwise = grouped conv with
    groups == channels (XLA lowers this to a channel-parallel conv)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: max(int(c * scale), 8)
        cfg = [(s(32), s(64), 1), (s(64), s(128), 2), (s(128), s(128), 1),
               (s(128), s(256), 2), (s(256), s(256), 1),
               (s(256), s(512), 2)] + [(s(512), s(512), 1)] * 5 + \
              [(s(512), s(1024), 2), (s(1024), s(1024), 1)]
        blocks = [_conv_bn(3, s(32), 3, stride=2, padding=1)]
        for c_in, c_out, stride in cfg:
            blocks.append(_conv_bn(c_in, c_in, 3, stride=stride, padding=1,
                                   groups=c_in))        # depthwise
            blocks.append(_conv_bn(c_in, c_out, 1))      # pointwise
        self.features = Sequential(*blocks)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(_flatten1(x))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


class _InvertedResidual(Layer):
    def __init__(self, c_in, c_out, stride, expand_ratio):
        super().__init__()
        hidden = int(round(c_in * expand_ratio))
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(c_in, hidden, 1, act=ReLU6))
        layers += [
            _conv_bn(hidden, hidden, 3, stride=stride, padding=1,
                     groups=hidden, act=ReLU6),
            _conv_bn(hidden, c_out, 1, act=None),
        ]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        s = lambda c: max(int(c * scale), 8)
        c_in = s(32)
        blocks = [_conv_bn(3, c_in, 3, stride=2, padding=1, act=ReLU6)]
        for t, c, n, stride in cfg:
            for i in range(n):
                blocks.append(_InvertedResidual(
                    c_in, s(c), stride if i == 0 else 1, t))
                c_in = s(c)
        last = max(s(1280), 1280) if scale > 1.0 else 1280
        blocks.append(_conv_bn(c_in, last, 1, act=ReLU6))
        self.features = Sequential(*blocks)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2),
                                         Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(_flatten1(x))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


class _SEModule(Layer):
    def __init__(self, ch, reduction=4):
        super().__init__()
        self.pool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(ch, ch // reduction, 1)
        self.fc2 = Conv2D(ch // reduction, ch, 1)
        self.relu = ReLU()
        self.hsig = Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _MBV3Block(Layer):
    def __init__(self, c_in, c_mid, c_out, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if c_mid != c_in:
            layers.append(_conv_bn(c_in, c_mid, 1, act=act))
        layers.append(_conv_bn(c_mid, c_mid, k, stride=stride,
                               padding=k // 2, groups=c_mid, act=act))
        if se:
            layers.append(_SEModule(c_mid))
        layers.append(_conv_bn(c_mid, c_out, 1, act=None))
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class _MobileNetV3(Layer):
    def __init__(self, cfg, last_ch, num_classes=1000, with_pool=True,
                 scale=1.0):
        super().__init__()
        s = lambda c: max(int(c * scale), 8)
        c_in = s(16)
        blocks = [_conv_bn(3, c_in, 3, stride=2, padding=1, act=Hardswish)]
        for k, mid, out, se, act, stride in cfg:
            blocks.append(_MBV3Block(c_in, s(mid), s(out), k, stride, se,
                                     act))
            c_in = s(out)
        last_conv = s(cfg[-1][1])
        blocks.append(_conv_bn(c_in, last_conv, 1, act=Hardswish))
        self.features = Sequential(*blocks)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(last_conv, last_ch), Hardswish(), Dropout(0.2),
                Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(_flatten1(x))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        cfg = [  # k, exp, out, SE, act, stride
            (3, 16, 16, True, ReLU, 2), (3, 72, 24, False, ReLU, 2),
            (3, 88, 24, False, ReLU, 1), (5, 96, 40, True, Hardswish, 2),
            (5, 240, 40, True, Hardswish, 1),
            (5, 240, 40, True, Hardswish, 1),
            (5, 120, 48, True, Hardswish, 1),
            (5, 144, 48, True, Hardswish, 1),
            (5, 288, 96, True, Hardswish, 2),
            (5, 576, 96, True, Hardswish, 1),
            (5, 576, 96, True, Hardswish, 1)]
        super().__init__(cfg, 1024, num_classes, with_pool, scale)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        cfg = [
            (3, 16, 16, False, ReLU, 1), (3, 64, 24, False, ReLU, 2),
            (3, 72, 24, False, ReLU, 1), (5, 72, 40, True, ReLU, 2),
            (5, 120, 40, True, ReLU, 1), (5, 120, 40, True, ReLU, 1),
            (3, 240, 80, False, Hardswish, 2),
            (3, 200, 80, False, Hardswish, 1),
            (3, 184, 80, False, Hardswish, 1),
            (3, 184, 80, False, Hardswish, 1),
            (3, 480, 112, True, Hardswish, 1),
            (3, 672, 112, True, Hardswish, 1),
            (5, 672, 160, True, Hardswish, 2),
            (5, 960, 160, True, Hardswish, 1),
            (5, 960, 160, True, Hardswish, 1)]
        super().__init__(cfg, 1280, num_classes, with_pool, scale)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


class _Fire(Layer):
    def __init__(self, c_in, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Sequential(Conv2D(c_in, squeeze, 1), ReLU())
        self.expand1 = Sequential(Conv2D(squeeze, e1, 1), ReLU())
        self.expand3 = Sequential(Conv2D(squeeze, e3, 3, padding=1), ReLU())

    def forward(self, x):
        from ..ops.manipulation import concat
        s = self.squeeze(x)
        return concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        if version == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(), MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                MaxPool2D(3, 2), _Fire(128, 32, 128, 128),
                _Fire(256, 32, 128, 128), MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.num_classes = num_classes
        self.with_pool = with_pool
        if num_classes > 0:
            self.classifier = Sequential(
                Dropout(0.5), Conv2D(512, num_classes, 1), ReLU())
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return _flatten1(x)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)


def _channel_shuffle(x, groups):
    from ..ops.manipulation import reshape, transpose
    b, c, h, w = x.shape
    x = reshape(x, [b, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [b, c, h, w])


class _ShuffleUnit(Layer):
    def __init__(self, c_in, c_out, stride, act=ReLU):
        super().__init__()
        self.stride = stride
        branch = c_out // 2
        if stride == 2:
            self.branch1 = Sequential(
                _conv_bn(c_in, c_in, 3, stride=2, padding=1, groups=c_in,
                         act=None),
                _conv_bn(c_in, branch, 1, act=act))
            c_in2 = c_in
        else:
            self.branch1 = None
            c_in2 = c_in // 2
        self.branch2 = Sequential(
            _conv_bn(c_in2, branch, 1, act=act),
            _conv_bn(branch, branch, 3, stride=stride, padding=1,
                     groups=branch, act=None),
            _conv_bn(branch, branch, 1, act=act))

    def forward(self, x):
        from ..ops.manipulation import concat, split
        if self.stride == 2:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        stage_out = {0.25: [24, 48, 96, 512], 0.33: [32, 64, 128, 512],
                     0.5: [48, 96, 192, 1024], 1.0: [116, 232, 464, 1024],
                     1.5: [176, 352, 704, 1024],
                     2.0: [244, 488, 976, 2048]}[scale]
        repeats = [4, 8, 4]
        from ..nn.layer.activation import Swish
        act_cls = {"relu": ReLU, "swish": Swish}[act]
        self.conv1 = _conv_bn(3, 24, 3, stride=2, padding=1, act=act_cls)
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        c_in = 24
        stages = []
        for r, c_out in zip(repeats, stage_out[:3]):
            units = [_ShuffleUnit(c_in, c_out, 2, act=act_cls)]
            for _ in range(r - 1):
                units.append(_ShuffleUnit(c_out, c_out, 1, act=act_cls))
            stages.append(Sequential(*units))
            c_in = c_out
        self.stages = Sequential(*stages)
        self.conv_last = _conv_bn(c_in, stage_out[3], 1, act=act_cls)
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(stage_out[3], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(_flatten1(x))
        return x


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


class _DenseLayer(Layer):
    def __init__(self, c_in, growth_rate, bn_size):
        super().__init__()
        self.bn1 = BatchNorm2D(c_in)
        self.conv1 = Conv2D(c_in, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3,
                            padding=1, bias_attr=False)
        self.relu = ReLU()

    def forward(self, x):
        from ..ops.manipulation import concat
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        return concat([x, out], axis=1)


class DenseNet(Layer):
    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000, with_pool=True):
        super().__init__()
        block_cfg = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
                     169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
                     264: (6, 12, 64, 48)}[layers]
        num_init = 2 * growth_rate
        self.stem = Sequential(
            Conv2D(3, num_init, 7, stride=2, padding=3, bias_attr=False),
            BatchNorm2D(num_init), ReLU(), MaxPool2D(3, stride=2, padding=1))
        blocks = []
        ch = num_init
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(ch, growth_rate, bn_size))
                ch += growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(Sequential(
                    BatchNorm2D(ch), ReLU(),
                    Conv2D(ch, ch // 2, 1, bias_attr=False),
                    AvgPool2D(2, stride=2)))
                ch //= 2
        self.blocks = Sequential(*blocks)
        self.bn_last = BatchNorm2D(ch)
        self.relu = ReLU()
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_last(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(_flatten1(x))
        return x


def densenet121(pretrained=False, **kwargs):
    return DenseNet(121, **kwargs)


class _Inception(Layer):
    def __init__(self, c_in, c1, c3r, c3, c5r, c5, pp):
        super().__init__()
        self.b1 = Sequential(Conv2D(c_in, c1, 1), ReLU())
        self.b2 = Sequential(Conv2D(c_in, c3r, 1), ReLU(),
                             Conv2D(c3r, c3, 3, padding=1), ReLU())
        self.b3 = Sequential(Conv2D(c_in, c5r, 1), ReLU(),
                             Conv2D(c5r, c5, 5, padding=2), ReLU())
        self.b4 = Sequential(MaxPool2D(3, stride=1, padding=1),
                             Conv2D(c_in, pp, 1), ReLU())

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            Conv2D(3, 64, 7, stride=2, padding=3), ReLU(),
            MaxPool2D(3, stride=2, padding=1),
            Conv2D(64, 64, 1), ReLU(),
            Conv2D(64, 192, 3, padding=1), ReLU(),
            MaxPool2D(3, stride=2, padding=1))
        self.inc3 = Sequential(
            _Inception(192, 64, 96, 128, 16, 32, 32),
            _Inception(256, 128, 128, 192, 32, 96, 64),
            MaxPool2D(3, stride=2, padding=1))
        self.inc4 = Sequential(
            _Inception(480, 192, 96, 208, 16, 48, 64),
            _Inception(512, 160, 112, 224, 24, 64, 64),
            _Inception(512, 128, 128, 256, 24, 64, 64),
            _Inception(512, 112, 144, 288, 32, 64, 64),
            _Inception(528, 256, 160, 320, 32, 128, 128),
            MaxPool2D(3, stride=2, padding=1))
        self.inc5 = Sequential(
            _Inception(832, 256, 160, 320, 32, 128, 128),
            _Inception(832, 384, 192, 384, 48, 128, 128))
        self.with_pool = with_pool
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        self.num_classes = num_classes
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = self.inc5(self.inc4(self.inc3(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(_flatten1(x)))
        return x


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=4, groups=64, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=4, groups=64, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, width=4, groups=32, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, width=4, groups=64, **kwargs)


def densenet161(pretrained=False, **kwargs):
    kwargs.setdefault("growth_rate", 48)
    return DenseNet(layers=161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return DenseNet(layers=169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return DenseNet(layers=201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return DenseNet(layers=264, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)


class _InceptionA(Layer):
    def __init__(self, c_in, pool_feat):
        super().__init__()
        self.b1 = _conv_bn(c_in, 64, 1)
        self.b5 = Sequential(_conv_bn(c_in, 48, 1),
                             _conv_bn(48, 64, 5, padding=2))
        self.b3 = Sequential(_conv_bn(c_in, 64, 1),
                             _conv_bn(64, 96, 3, padding=1),
                             _conv_bn(96, 96, 3, padding=1))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             _conv_bn(c_in, pool_feat, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _InceptionB(Layer):
    """Grid reduction 35->17."""

    def __init__(self, c_in):
        super().__init__()
        self.b3 = _conv_bn(c_in, 384, 3, stride=2)
        self.b3d = Sequential(_conv_bn(c_in, 64, 1),
                              _conv_bn(64, 96, 3, padding=1),
                              _conv_bn(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class _InceptionC(Layer):
    def __init__(self, c_in, c7):
        super().__init__()
        self.b1 = _conv_bn(c_in, 192, 1)
        self.b7 = Sequential(
            _conv_bn(c_in, c7, 1),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(
            _conv_bn(c_in, c7, 1),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             _conv_bn(c_in, 192, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], 1)


class _InceptionD(Layer):
    """Grid reduction 17->8."""

    def __init__(self, c_in):
        super().__init__()
        self.b3 = Sequential(_conv_bn(c_in, 192, 1),
                             _conv_bn(192, 320, 3, stride=2))
        self.b7 = Sequential(
            _conv_bn(c_in, 192, 1),
            _conv_bn(192, 192, (1, 7), padding=(0, 3)),
            _conv_bn(192, 192, (7, 1), padding=(3, 0)),
            _conv_bn(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        from ..ops.manipulation import concat
        return concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _InceptionE(Layer):
    def __init__(self, c_in):
        super().__init__()
        self.b1 = _conv_bn(c_in, 320, 1)
        self.b3_stem = _conv_bn(c_in, 384, 1)
        self.b3_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = Sequential(_conv_bn(c_in, 448, 1),
                                   _conv_bn(448, 384, 3, padding=1))
        self.b3d_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             _conv_bn(c_in, 192, 1))

    def forward(self, x):
        from ..ops.manipulation import concat
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s), self.b3_b(s)], 1),
                       concat([self.b3d_a(d), self.b3d_b(d)], 1),
                       self.bp(x)], 1)


class InceptionV3(Layer):
    """Inception-v3 (Szegedy et al. 2015), 299x299 input — role of
    paddle.vision.models.InceptionV3 (reference mount empty)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _conv_bn(3, 32, 3, stride=2),
            _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, padding=1),
            MaxPool2D(3, stride=2),
            _conv_bn(64, 80, 1),
            _conv_bn(80, 192, 3),
            MaxPool2D(3, stride=2))
        self.blocks = Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(_flatten1(x)))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)


__all__ += ["resnext50_64x4d", "resnext101_64x4d", "resnext152_32x4d",
            "resnext152_64x4d", "densenet161", "densenet169",
            "densenet201", "densenet264", "shufflenet_v2_x0_25",
            "shufflenet_v2_x0_33", "shufflenet_v2_x0_5",
            "shufflenet_v2_x1_5", "shufflenet_v2_x2_0",
            "shufflenet_v2_swish", "InceptionV3", "inception_v3"]
