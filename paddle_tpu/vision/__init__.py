"""``paddle.vision`` — models / transforms / datasets / detection ops
(upstream ``python/paddle/vision/``, UNVERIFIED paths; see SURVEY.md
provenance warning)."""

from . import transforms
from . import models
from . import datasets
from . import ops
from .models import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152, LeNet, AlexNet, alexnet, VGG, vgg11, vgg13,
                     vgg16, vgg19, MobileNetV1, mobilenet_v1, MobileNetV2,
                     mobilenet_v2, MobileNetV3Small, MobileNetV3Large,
                     mobilenet_v3_small, mobilenet_v3_large, SqueezeNet,
                     squeezenet1_0, squeezenet1_1, ShuffleNetV2,
                     shufflenet_v2_x1_0, DenseNet, densenet121, GoogLeNet,
                     googlenet, resnext50_32x4d, resnext101_32x4d,
                     wide_resnet50_2, wide_resnet101_2, BasicBlock,
                     BottleneckBlock, resnext50_64x4d, resnext101_64x4d,
                     resnext152_32x4d, resnext152_64x4d, densenet161,
                     densenet169, densenet201, densenet264,
                     shufflenet_v2_x0_25, shufflenet_v2_x0_33,
                     shufflenet_v2_x0_5, shufflenet_v2_x1_5,
                     shufflenet_v2_x2_0, shufflenet_v2_swish,
                     InceptionV3, inception_v3)


def set_image_backend(backend):
    """paddle.vision.set_image_backend — 'pil' is the only bundled backend
    (cv2 is not in this image)."""
    if backend not in ("pil",):
        raise ValueError(f"unsupported image backend {backend!r}; only "
                         "'pil' is available in this environment")


def get_image_backend():
    return "pil"


def image_load(path, backend=None):
    from .datasets import _default_loader
    return _default_loader(path)


__all__ = ["transforms", "models", "datasets", "ops",
           "set_image_backend", "get_image_backend", "image_load"]
__all__ += models.__all__
