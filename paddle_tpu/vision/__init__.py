"""``paddle.vision`` — models/transforms/datasets scaffold
(python/paddle/vision/ parity, UNVERIFIED). Round-1 scope: ResNet family +
basic transforms + ops used by OpTest-style suites."""

from . import transforms
from . import models
from .models import ResNet, resnet18, resnet34, resnet50, resnet101, LeNet

__all__ = ["transforms", "models", "ResNet", "resnet18", "resnet34",
           "resnet50", "resnet101", "LeNet"]
