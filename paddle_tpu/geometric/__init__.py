"""paddle.geometric — graph-learning message passing ops.

Reference surface: upstream ``python/paddle/geometric/`` (UNVERIFIED; see
SURVEY.md provenance warning): message_passing (send_u_recv, send_ue_recv,
send_uv), math (segment_sum/mean/max/min), and graph sampling/reindexing.
The CUDA scatter kernels become ``jax.ops.segment_*`` (XLA lowers these to
sorted-scatter, TPU-friendly); sampling — inherently dynamic-shaped — is an
eager/host path, matching its data-prep role.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply
from ..ops.common import as_tensor

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min", "sample_neighbors",
           "reindex_graph", "weighted_sample_neighbors",
           "reindex_heter_graph"]

_SEG = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled explicitly
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}

_MESSAGE_OPS = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
                "div": jnp.divide}


def _segment_reduce(data, ids, pool_type, num_segments):
    pool_type = pool_type.lower()
    if pool_type == "mean":
        s = jax.ops.segment_sum(data, ids, num_segments)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                                  ids, num_segments)
        return s / jnp.maximum(cnt, 1.0)[(...,) + (None,) * (data.ndim - 1)]
    out = _SEG[pool_type](data, ids, num_segments)
    if pool_type in ("max", "min"):
        # empty segments produce +-inf in jax; paddle semantics: 0
        out = jnp.where(jnp.isfinite(out), out, jnp.zeros_like(out))
    return out


def _out_size(out_size, dst, x_rows):
    if out_size is not None:
        return int(out_size)
    return x_rows


def _make_segment_op(pool_type):
    def op(data, segment_ids, name=None, num_segments=None):
        d = as_tensor(data)
        ids = as_tensor(segment_ids)
        if num_segments is not None:
            n = int(num_segments)
        else:
            arr = ids.jax()
            if isinstance(arr, jax.core.Tracer):
                # ConcretizationTypeError so to_static treats this as a
                # graph break (eager fallback) instead of a hard error
                raise jax.errors.ConcretizationTypeError(
                    arr,
                    f"segment_{pool_type}: cannot infer the segment count "
                    "from traced segment_ids; pass num_segments= to keep "
                    "this op inside a compiled graph")
            n = int(np.asarray(arr).max()) + 1 if ids.shape[0] else 0
        return apply(lambda a, i: _segment_reduce(a, i, pool_type, n),
                     d, ids, name=f"segment_{pool_type}")
    op.__name__ = f"segment_{pool_type}"
    op.__doc__ = (f"Segment {pool_type} over the leading axis "
                  f"(paddle.geometric.segment_{pool_type}). The inferred "
                  f"segment count is eager-only; pass num_segments when "
                  f"tracing.")
    return op


segment_sum = _make_segment_op("sum")
segment_mean = _make_segment_op("mean")
segment_max = _make_segment_op("max")
segment_min = _make_segment_op("min")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] along edges and segment-reduce onto dst
    (paddle.geometric.send_u_recv)."""
    xt = as_tensor(x)
    n = _out_size(out_size, dst_index, int(xt.shape[0]))

    def fn(xa, src, dst):
        return _segment_reduce(xa[src], dst, reduce_op, n)

    return apply(fn, xt, as_tensor(src_index), as_tensor(dst_index),
                 name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features x[src] with edge features y (add/sub/mul/div),
    then segment-reduce onto dst."""
    xt = as_tensor(x)
    n = _out_size(out_size, dst_index, int(xt.shape[0]))
    mop = _MESSAGE_OPS[message_op.lower()]

    def fn(xa, ya, src, dst):
        return _segment_reduce(mop(xa[src], ya), dst, reduce_op, n)

    return apply(fn, xt, as_tensor(y), as_tensor(src_index),
                 as_tensor(dst_index), name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints: op(x[src], y[dst])."""
    mop = _MESSAGE_OPS[message_op.lower()]

    def fn(xa, ya, src, dst):
        return mop(xa[src], ya[dst])

    return apply(fn, as_tensor(x), as_tensor(y), as_tensor(src_index),
                 as_tensor(dst_index), name="send_uv")


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniformly sample up to sample_size neighbors per input node from a
    CSC graph (host-side eager op — sampling is data prep, not a compiled
    kernel). Reproducible under ``paddle.seed`` via the framework RNG."""
    from ..framework import random as framework_random
    sub = np.asarray(framework_random.next_key())
    rng = np.random.RandomState(int(sub[-1]) & 0x7FFFFFFF)
    row_np = np.asarray(as_tensor(row).numpy())
    colptr_np = np.asarray(as_tensor(colptr).numpy())
    nodes = np.asarray(as_tensor(input_nodes).numpy())
    eids_np = np.asarray(as_tensor(eids).numpy()) if eids is not None \
        else None
    out_neigh, out_cnt, out_eids = [], [], []
    for v in nodes:
        beg, end = int(colptr_np[v]), int(colptr_np[v + 1])
        neigh = row_np[beg:end]
        ids = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            pick = rng.choice(len(neigh), sample_size, replace=False)
            neigh, ids = neigh[pick], ids[pick]
        out_neigh.append(neigh)
        out_cnt.append(len(neigh))
        if eids_np is not None:
            out_eids.append(eids_np[ids])
    neigh = np.concatenate(out_neigh) if out_neigh else np.zeros(0, "int64")
    cnt = np.asarray(out_cnt, "int32")
    res = (Tensor(jnp.asarray(neigh)), Tensor(jnp.asarray(cnt)))
    if return_eids:
        ei = np.concatenate(out_eids) if out_eids else np.zeros(0, "int64")
        res += (Tensor(jnp.asarray(ei)),)
    return res


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Relabel a sampled subgraph to contiguous ids: x first, then new
    neighbor nodes in first-seen order (host-side eager op)."""
    x_np = np.asarray(as_tensor(x).numpy())
    neigh = np.asarray(as_tensor(neighbors).numpy())
    cnt = np.asarray(as_tensor(count).numpy())
    mapping: dict[int, int] = {int(v): i for i, v in enumerate(x_np)}
    for v in neigh:
        if int(v) not in mapping:
            mapping[int(v)] = len(mapping)
    reindex_src = np.asarray([mapping[int(v)] for v in neigh], "int64")
    # edges are (neighbor -> center); centers repeat per their count
    reindex_dst = np.repeat(np.arange(len(x_np), dtype="int64"), cnt)
    nodes = np.asarray(sorted(mapping, key=mapping.get), "int64")
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(nodes)))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted neighbor sampling from a CSC graph: each neighbor is drawn
    without replacement with probability proportional to its edge weight
    (host-side eager op like ``sample_neighbors``; paddle.geometric
    parity, reference mount empty)."""
    from ..framework import random as framework_random
    sub = np.asarray(framework_random.next_key())
    rng = np.random.RandomState(int(sub[-1]) & 0x7FFFFFFF)
    row_np = np.asarray(as_tensor(row).numpy())
    colptr_np = np.asarray(as_tensor(colptr).numpy())
    w_np = np.asarray(as_tensor(edge_weight).numpy(), dtype="float64")
    nodes = np.asarray(as_tensor(input_nodes).numpy())
    eids_np = np.asarray(as_tensor(eids).numpy()) if eids is not None \
        else None
    out_neigh, out_cnt, out_eids = [], [], []
    for v in nodes:
        beg, end = int(colptr_np[v]), int(colptr_np[v + 1])
        neigh = row_np[beg:end]
        ids = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            w = np.clip(w_np[beg:end], 0.0, None)
            tot = w.sum()
            if tot > 0:
                # zero-weight edges are never picked; if fewer positive
                # edges than sample_size, take just those (no crash)
                pos = np.flatnonzero(w)
                k = min(sample_size, len(pos))
                pick = rng.choice(pos, k, replace=False, p=w[pos] / tot)
            else:
                pick = rng.choice(len(neigh), sample_size, replace=False)
            neigh, ids = neigh[pick], ids[pick]
        out_neigh.append(neigh)
        out_cnt.append(len(neigh))
        if eids_np is not None:
            out_eids.append(eids_np[ids])
    neigh = np.concatenate(out_neigh) if out_neigh else np.zeros(0, "int64")
    cnt = np.asarray(out_cnt, "int32")
    res = (Tensor(jnp.asarray(neigh)), Tensor(jnp.asarray(cnt)))
    if return_eids:
        ei = np.concatenate(out_eids) if out_eids else np.zeros(0, "int64")
        res += (Tensor(jnp.asarray(ei)),)
    return res


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Relabel sampled subgraphs of a heterogeneous graph: ``neighbors``/
    ``count`` are per-edge-type lists sharing ONE node-id space; the
    mapping (x first, then first-seen order ACROSS types) is shared so the
    per-type edge lists stay consistent."""
    x_np = np.asarray(as_tensor(x).numpy())
    neighs = [np.asarray(as_tensor(n).numpy()) for n in neighbors]
    cnts = [np.asarray(as_tensor(c).numpy()) for c in count]
    mapping: dict[int, int] = {int(v): i for i, v in enumerate(x_np)}
    for neigh in neighs:
        for v in neigh:
            if int(v) not in mapping:
                mapping[int(v)] = len(mapping)
    srcs, dsts = [], []
    for neigh, cnt in zip(neighs, cnts):
        srcs.append(np.asarray([mapping[int(v)] for v in neigh], "int64"))
        dsts.append(np.repeat(np.arange(len(x_np), dtype="int64"), cnt))
    reindex_src = np.concatenate(srcs) if srcs else np.zeros(0, "int64")
    reindex_dst = np.concatenate(dsts) if dsts else np.zeros(0, "int64")
    nodes = np.asarray(sorted(mapping, key=mapping.get), "int64")
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(nodes)))
