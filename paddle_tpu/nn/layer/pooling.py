"""Pooling layers (python/paddle/nn/layer/pooling.py parity, UNVERIFIED)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D"]


class _Pool(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 **kw):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.kw = kw


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            ceil_mode=self.ceil_mode)


class _AdaptivePool(Layer):
    def __init__(self, output_size, **kw):
        super().__init__()
        self.output_size = output_size


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._a
        return F.max_unpool1d(x, indices, k, s, p, df, osz)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._a
        return F.max_unpool2d(x, indices, k, s, p, df, osz)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._a = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._a
        return F.max_unpool3d(x, indices, k, s, p, df, osz)


__all__ += ["MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D"]


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self._a = (norm_type, kernel_size, stride, padding, ceil_mode,
                   data_format)

    def forward(self, x):
        p, k, s, pad, cm, df = self._a
        return F.lp_pool1d(x, p, k, s, pad, cm, df)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self._a = (norm_type, kernel_size, stride, padding, ceil_mode,
                   data_format)

    def forward(self, x):
        p, k, s, pad, cm, df = self._a
        return F.lp_pool2d(x, p, k, s, pad, cm, df)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._a = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        osz, k, u, rm = self._a
        return F.fractional_max_pool2d(x, osz, k, u, rm)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._a = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        osz, k, u, rm = self._a
        return F.fractional_max_pool3d(x, osz, k, u, rm)


__all__ += ["LPPool1D", "LPPool2D", "FractionalMaxPool2D",
            "FractionalMaxPool3D"]
