"""``paddle.nn.Layer`` — the module system (python/paddle/nn/layer/layers.py
parity, UNVERIFIED).  Layers are mutable containers of Parameters/buffers/
sublayers with hooks and state_dict; execution stays functional underneath
(parameters are persistable Tensors the jit functionalizer captures)."""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, Parameter, to_jax_dtype, is_floating
from ...framework.default_dtype import get_default_dtype
from .. import initializer as I

__all__ = ["Layer"]


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self) -> None:
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self.training = True
        self._dtype = to_jax_dtype(dtype) if dtype else get_default_dtype()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ---- attribute routing ----------------------------------------------

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError(
                    "call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError(
                    "call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        elif buffers is not None and name in buffers:
            if value is None:
                buffers[name] = None
            else:
                buffers[name] = value if isinstance(value, Tensor) \
                    else Tensor(value)
                buffers[name].persistable = True
        else:
            if params is not None and name in params and value is None:
                params.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # ---- construction helpers -------------------------------------------

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Mirrors Layer.create_parameter: resolves ParamAttr + initializer."""
        from ..param_attr import ParamAttr
        dtype = to_jax_dtype(dtype) if dtype is not None else self._dtype
        attr = ParamAttr._to_attr(attr)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = I.global_initializer(is_bias)
            if init is None:
                init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(tuple(int(s) for s in shape), dtype)
        trainable = attr.trainable if attr is not None else True
        p = Parameter(data, trainable=trainable,
                      name=(attr.name if attr is not None else "") or "")
        if attr is not None:
            p.optimize_attr = {"learning_rate": attr.learning_rate}
            p.regularizer = attr.regularizer
        return p

    def add_parameter(self, name: str, parameter: Parameter | None):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Tensor | None,
                        persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        if tensor is not None:
            tensor.persistable = True
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- iteration -------------------------------------------------------

    def parameters(self, include_sublayers: bool = True) -> list[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "",
                         include_sublayers: bool = True
                         ) -> Iterator[tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers: bool = True) -> list[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[tuple[str, Tensor]]:
        seen = set()
        for name, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[tuple[str, "Layer"]]:
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self: bool = False) -> list["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self.named_children():
            if layer is None or id(layer) in layers_set:
                continue
            layers_set.add(id(layer))
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(prefix=sub_prefix,
                                             include_self=False,
                                             layers_set=layers_set)

    def _walk(self, prefix: str, include_sublayers: bool):
        yield prefix, self
        if include_sublayers:
            yield from self.named_sublayers(prefix=prefix)

    # ---- modes / transforms ---------------------------------------------

    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable[["Layer"], None]):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = to_jax_dtype(dtype)
            for p in self.parameters():
                if is_floating(p.dtype):
                    p.set_data(p._data.astype(dtype))
            for b in self.buffers():
                if is_floating(b.dtype):
                    b.set_data(b._data.astype(dtype))
            for l in self.sublayers(include_self=True):
                l._dtype = dtype
        return self

    def astype(self, dtype=None):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    # ---- state dict ------------------------------------------------------

    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in [("", self)] + (
                list(self.named_sublayers(
                    prefix=structured_name_prefix.rstrip(".")))
                if include_sublayers else []):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                key = f"{name}.{bname}" if name else bname
                dest[key] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for key, target in own.items():
            if key in state_dict:
                src = state_dict[key]
                data = src._data if isinstance(src, Tensor) else \
                    jnp.asarray(np.asarray(src))
                if tuple(data.shape) != tuple(target._data.shape):
                    raise ValueError(
                        f"shape mismatch for {key}: loaded "
                        f"{tuple(data.shape)} vs param "
                        f"{tuple(target._data.shape)}")
                target.set_data(data.astype(target.dtype))
            else:
                missing.append(key)
        for key in state_dict:
            if key not in own:
                unexpected.append(key)
        return missing, unexpected

    load_dict = set_state_dict

    # ---- hooks -----------------------------------------------------------

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call ------------------------------------------------------------

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # ---- misc ------------------------------------------------------------

    def full_name(self) -> str:
        return self._name_scope

    def clear_gradients(self) -> None:
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, child in self.named_children():
            mod_str = repr(child)
            mod_str = "\n".join(
                ["  " + l for l in mod_str.split("\n")])
            lines.append(f"  ({name}): {mod_str.strip()}" if "\n" not in
                         mod_str else f"  ({name}): {mod_str.lstrip()}")
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
