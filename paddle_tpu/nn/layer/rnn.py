"""Recurrent layers (python/paddle/nn/layer/rnn.py parity, UNVERIFIED).

TPU-first: the time loop is a single ``jax.lax.scan`` inside one traced op,
so the whole sequence compiles to one XLA while-loop (no per-step dispatch),
and the MXU sees batched [B, 4H] gate matmuls."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...ops.common import as_tensor
from .. import initializer as I
from .layers import Layer

__all__ = ["SimpleRNN", "LSTM", "GRU", "SimpleRNNCell", "LSTMCell",
           "GRUCell", "RNN", "BiRNN"]


class _RNNCellBase(Layer):
    def __init__(self, input_size, hidden_size, gates, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [gates * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=u)
        self.weight_hh = self.create_parameter(
            [gates * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=u)
        if bias_ih_attr is not False:
            self.bias_ih = self.create_parameter(
                [gates * hidden_size], attr=bias_ih_attr, is_bias=True,
                default_initializer=u)
        else:
            self.bias_ih = None
        if bias_hh_attr is not False:
            self.bias_hh = self.create_parameter(
                [gates * hidden_size], attr=bias_hh_attr, is_bias=True,
                default_initializer=u)
        else:
            self.bias_hh = None

    def _params(self):
        ps = [self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            ps.append(self.bias_ih)
        if self.bias_hh is not None:
            ps.append(self.bias_hh)
        return ps

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        from ...ops.creation import full
        return full([b, self.hidden_size], init_value, dtype or "float32")


def _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih
    if b_hh is not None:
        gates = gates + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh):
    gi = x_t @ w_ih.T + (b_ih if b_ih is not None else 0)
    gh = h @ w_hh.T + (b_hh if b_hh is not None else 0)
    ir, iz, ic = jnp.split(gi, 3, axis=-1)
    hr, hz, hc = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(ic + r * hc)
    return n + z * (h - n)


def _rnn_step(x_t, h, w_ih, w_hh, b_ih, b_hh, act):
    out = x_t @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        out = out + b_ih
    if b_hh is not None:
        out = out + b_hh
    return jnp.tanh(out) if act == "tanh" else jax.nn.relu(out)


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size, 1, **kw)
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        args = [as_tensor(inputs), as_tensor(states)] + self._params()
        act = self.activation
        has_bi, has_bh = self.bias_ih is not None, self.bias_hh is not None

        def fn(x, h, w_ih, w_hh, *bs):
            b_ih = bs[0] if has_bi else None
            b_hh = bs[1 if has_bi else 0] if has_bh else None
            return _rnn_step(x, h, w_ih, w_hh, b_ih, b_hh, act)
        out = apply(fn, *args, name="simple_rnn_cell")
        return out, out


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 4, **kw)

    def forward(self, inputs, states=None):
        if states is None:
            b = inputs.shape[0]
            from ...ops.creation import zeros
            states = (zeros([b, self.hidden_size]),
                      zeros([b, self.hidden_size]))
        h0, c0 = states
        args = [as_tensor(inputs), as_tensor(h0), as_tensor(c0)] + \
            self._params()
        has_bi, has_bh = self.bias_ih is not None, self.bias_hh is not None

        def fn(x, h, c, w_ih, w_hh, *bs):
            b_ih = bs[0] if has_bi else None
            b_hh = bs[1 if has_bi else 0] if has_bh else None
            return _lstm_step(x, h, c, w_ih, w_hh, b_ih, b_hh)
        h_new, c_new = apply(fn, *args, n_outputs=2, name="lstm_cell")
        return h_new, (h_new, c_new)


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 3, **kw)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        args = [as_tensor(inputs), as_tensor(states)] + self._params()
        has_bi, has_bh = self.bias_ih is not None, self.bias_hh is not None

        def fn(x, h, w_ih, w_hh, *bs):
            b_ih = bs[0] if has_bi else None
            b_hh = bs[1 if has_bi else 0] if has_bh else None
            return _gru_step(x, h, w_ih, w_hh, b_ih, b_hh)
        out = apply(fn, *args, name="gru_cell")
        return out, out


class RNN(Layer):
    """Wraps a cell; runs it over time with lax.scan."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # delegate to the layer-mode runner in _RNNLayerBase style
        raise NotImplementedError(
            "Use SimpleRNN/LSTM/GRU layers; RNN cell wrapper supports "
            "step-by-step use via self.cell")


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.time_major = time_major


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        gates = {"lstm": 4, "gru": 3, "rnn": 1}[mode]
        cell_cls = {"lstm": LSTMCell, "gru": GRUCell,
                    "rnn": SimpleRNNCell}[mode]
        self.cells = []
        for layer_i in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer_i == 0 else hidden_size * ndir
                kw = dict(weight_ih_attr=weight_ih_attr,
                          weight_hh_attr=weight_hh_attr,
                          bias_ih_attr=bias_ih_attr,
                          bias_hh_attr=bias_hh_attr)
                if mode == "rnn":
                    cell = cell_cls(in_sz, hidden_size, activation, **kw)
                else:
                    cell = cell_cls(in_sz, hidden_size, **kw)
                self.add_sublayer(f"cell_{layer_i}_{d}", cell)
                self.cells.append(cell)

    def _scan_layer(self, cell, x, reverse):
        """x: Tensor [B, T, I] (batch-first internally). One traced op."""
        is_lstm = self.mode == "lstm"
        mode, act = self.mode, self.activation
        has_bi = cell.bias_ih is not None
        has_bh = cell.bias_hh is not None

        def fn(xx, w_ih, w_hh, *bs):
            b_ih = bs[0] if has_bi else None
            b_hh = bs[1 if has_bi else 0] if has_bh else None
            xt = jnp.swapaxes(xx, 0, 1)  # [T, B, I]
            if reverse:
                xt = jnp.flip(xt, 0)
            B = xt.shape[1]
            h0 = jnp.zeros((B, cell.hidden_size), xx.dtype)

            if is_lstm:
                def step(carry, x_t):
                    h, c = carry
                    h2, c2 = _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh)
                    return (h2, c2), h2
                (hT, cT), ys = jax.lax.scan(step, (h0, h0), xt)
                final = jnp.stack([hT, cT])
            else:
                def step(h, x_t):
                    if mode == "gru":
                        h2 = _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh)
                    else:
                        h2 = _rnn_step(x_t, h, w_ih, w_hh, b_ih, b_hh, act)
                    return h2, h2
                hT, ys = jax.lax.scan(step, h0, xt)
                final = hT[None]
            if reverse:
                ys = jnp.flip(ys, 0)
            return jnp.swapaxes(ys, 0, 1), final
        args = [x] + cell._params()
        ys, final = apply(fn, *args, n_outputs=2,
                          name=f"{mode}_layer")
        return ys, final

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as M
        x = as_tensor(inputs)
        if self.time_major:
            x = M.transpose(x, [1, 0, 2])
        finals = []
        out = x
        ndir = 2 if self.bidirect else 1
        for layer_i in range(self.num_layers):
            if self.bidirect:
                fw = self.cells[layer_i * 2]
                bw = self.cells[layer_i * 2 + 1]
                y_f, s_f = self._scan_layer(fw, out, False)
                y_b, s_b = self._scan_layer(bw, out, True)
                out = M.concat([y_f, y_b], axis=-1)
                finals.extend([s_f, s_b])
            else:
                cell = self.cells[layer_i]
                out, s = self._scan_layer(cell, out, False)
                finals.append(s)
            if self.dropout > 0 and layer_i < self.num_layers - 1:
                from .. import functional as F
                out = F.dropout(out, self.dropout, training=self.training)
        if self.time_major:
            out = M.transpose(out, [1, 0, 2])
        # final states: [num_layers*ndir, B, H] (+ cell for lstm)
        if self.mode == "lstm":
            h = M.stack([f[0] for f in finals], axis=0)
            c = M.stack([f[1] for f in finals], axis=0)
            return out, (h, c)
        h = M.concat(finals, axis=0)
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__("rnn", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("lstm", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("gru", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


#: public alias (paddle.nn.RNNCellBase) of the cell base class
RNNCellBase = _RNNCellBase
__all__ += ["RNNCellBase"]


class BeamSearchDecoder:
    """Beam-search decoding over an RNN cell (paddle.nn.BeamSearchDecoder).

    ``cell(inputs, states) -> (outputs, new_states)``; ``output_fn`` maps
    cell outputs to vocabulary logits; ``embedding_fn`` maps token ids to
    the next step's inputs. Drive it with ``paddle.nn.dynamic_decode`` —
    decode loops are host-driven, matching the reference's dygraph
    decoding (each step is still XLA-compiled compute).
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _expand(self, t):
        """[B, ...] -> [B*W, ...] by repeating each row W times."""
        from ...ops.manipulation import repeat_interleave
        return repeat_interleave(t, self.beam_size, axis=0)

    def initialize(self, initial_states):
        states = jax.tree.map(
            self._expand, initial_states,
            is_leaf=lambda v: isinstance(v, Tensor))
        any_leaf = jax.tree.leaves(
            states, is_leaf=lambda v: isinstance(v, Tensor))[0]
        bw = any_leaf.shape[0]
        from ...ops.creation import full
        ids = full([bw], self.start_token, "int64")
        # beam 0 active, beams 1..W-1 start muted so step 1 expands one beam
        import numpy as _np
        lp = _np.full((bw,), -1e9, _np.float32)
        lp[:: self.beam_size] = 0.0
        return ids, states, Tensor(jnp.asarray(lp))

    def step(self, ids, states, log_probs, finished=None):
        """One decode step over flattened [B*W] beams. Returns
        (ids, parent_beams, states, log_probs, finished_mask); parents
        are the source-beam indices each output beam extended — feed the
        (ids, parents) history to ``F.gather_tree`` to reconstruct full
        hypotheses (``dynamic_decode`` does this)."""
        inputs = self.embedding_fn(ids) if self.embedding_fn else ids
        out, new_states = self.cell(inputs, states)
        logits = self.output_fn(out) if self.output_fn else out
        logp = jax.nn.log_softmax(logits._data, axis=-1)
        W = self.beam_size
        V = logp.shape[-1]
        bw = logp.shape[0]
        B = bw // W

        if finished is not None and self.end_token >= 0:
            # freeze finished hypotheses: they may only emit end_token at
            # zero cost, so their score stays put and they stay rankable
            frozen = jnp.full((V,), -1e9, logp.dtype).at[
                self.end_token].set(0.0)
            logp = jnp.where(finished._data[:, None], frozen[None, :],
                             logp)

        total = logp + log_probs._data[:, None]            # [B*W, V]
        flat = total.reshape(B, W * V)
        top_lp, top_idx = jax.lax.top_k(flat, W)           # [B, W]
        beam = top_idx // V                                # source beam
        token = top_idx % V
        src = (jnp.arange(B)[:, None] * W + beam).reshape(-1)
        new_ids = Tensor(token.reshape(-1).astype(jnp.int64))
        gathered = jax.tree.map(
            lambda s: Tensor(jnp.take(s._data, src, axis=0)),
            new_states, is_leaf=lambda v: isinstance(v, Tensor))
        # a beam's finished flag follows its SOURCE hypothesis
        prev_fin = (jnp.zeros((bw,), bool) if finished is None
                    else jnp.take(finished._data, src))
        fin = prev_fin | (new_ids._data == self.end_token)
        return (new_ids, Tensor(beam.reshape(-1).astype(jnp.int64)),
                gathered, Tensor(top_lp.reshape(-1)), Tensor(fin))


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Run a decoder to completion (paddle.nn.dynamic_decode): returns
    (ids [B, W, T], final_log_probs [B, W]). Hypotheses are reconstructed
    through the parent-beam pointers with ``F.gather_tree`` — a beam's
    returned row is its full history, not a positional stitch."""
    from .. import functional as F

    ids, states, lp = decoder.initialize(inits)
    W = decoder.beam_size
    bw = ids.shape[0]
    B = bw // W
    id_steps, parent_steps = [], []
    fin = None
    for _ in range(int(max_step_num)):
        ids, parents, states, lp, fin = decoder.step(ids, states, lp, fin)
        id_steps.append(ids._data.reshape(B, W))
        parent_steps.append(parents._data.reshape(B, W))
        if bool(fin._data.all()):
            break
    seq = Tensor(jnp.stack(id_steps, axis=0))          # [T, B, W]
    par = Tensor(jnp.stack(parent_steps, axis=0))
    full = F.gather_tree(seq, par)                     # backtracked
    out = jnp.transpose(full._data, (1, 2, 0))         # [B, W, T]
    return Tensor(out), Tensor(lp._data.reshape(B, W))


__all__ += ["BeamSearchDecoder", "dynamic_decode"]
