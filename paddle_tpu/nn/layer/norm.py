"""Normalization layers (python/paddle/nn/layer/norm.py parity,
UNVERIFIED)."""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight,
                            self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, " \
               f"epsilon={self.epsilon}"


class RMSNorm(Layer):
    """Root-mean-square norm — the transformer hot path; fused Pallas kernel
    on TPU (SURVEY.md §2.1 fused rms_norm kernel)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            self.normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, self.training, self.momentum,
                            self.epsilon, self.data_format,
                            self.use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCL" else
                         "NHWC", use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCDHW" else
                         "NHWC", use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync across the data mesh axis happens inside the
    compiled program (psum over 'data') when running under shard_map; in
    GSPMD batch-sharded mode XLA computes global stats automatically for
    full-batch reductions, so this equals BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(
                    sub, SyncBatchNorm):
                new = SyncBatchNorm(sub.num_features, sub.momentum,
                                    sub.epsilon,
                                    data_format=sub.data_format)
                if sub.weight is not None:
                    new.weight.set_data(sub.weight._data)
                if sub.bias is not None:
                    new.bias.set_data(sub.bias._data)
                new._mean.set_data(sub._mean._data)
                new._variance.set_data(sub._variance._data)
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.weight = None
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               epsilon=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self.axis = axis
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = weight_shape[axis]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != axis:
                w *= s
        self.register_buffer("weight_u", Tensor(
            jnp.asarray(I.Normal(0, 1)((h,), dtype))))
        self.register_buffer("weight_v", Tensor(
            jnp.asarray(I.Normal(0, 1)((w,), dtype))))

    def forward(self, weight):
        from ..functional.norm import spectral_norm
        return spectral_norm(weight, self.weight_u, self.weight_v,
                             dim=self.axis, power_iters=self.power_iters,
                             eps=self.epsilon)
