"""Conv layers (python/paddle/nn/layer/conv.py parity, UNVERIFIED).
Weight layout matches paddle: [out_c, in_c/groups, *kernel]; transpose convs
use [in_c, out_c/groups, *kernel]."""

from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose"]


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n,
                 stride=1, padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NCHW", transpose=False, output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _tuplize(kernel_size, n)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self._n = n
        fan_in = in_channels // groups * int(np.prod(self.kernel_size))
        if transpose:
            w_shape = [in_channels, out_channels // groups,
                       *self.kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups,
                       *self.kernel_size]
        std = 1.0 / np.sqrt(fan_in)
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.Uniform(-std, std))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True,
                default_initializer=I.Uniform(-std, std))
        else:
            self.bias = None


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)
