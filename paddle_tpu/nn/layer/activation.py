"""Activation layers (python/paddle/nn/layer/activation.py parity,
UNVERIFIED)."""

from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax",
           "LogSoftmax", "LeakyReLU", "ELU", "SELU", "CELU", "Hardswish",
           "Hardsigmoid", "Hardtanh", "Hardshrink", "Softshrink",
           "Tanhshrink", "Mish", "PReLU", "RReLU", "Swish", "Silu",
           "Softplus", "Softsign", "ThresholdedReLU", "LogSigmoid",
           "Maxout", "GLU"]


def _simple(name, fn, **defaults):
    def __init__(self, name=None, **kwargs):
        Layer.__init__(self)
        self._kwargs = {**defaults, **kwargs}

    def forward(self, x):
        return fn(x, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _simple("ReLU", F.relu)
ReLU6 = _simple("ReLU6", F.relu6)
Sigmoid = _simple("Sigmoid", F.sigmoid)
Tanh = _simple("Tanh", F.tanh)
Hardswish = _simple("Hardswish", F.hardswish)
Mish = _simple("Mish", F.mish)
Softsign = _simple("Softsign", F.softsign)
LogSigmoid = _simple("LogSigmoid", F.log_sigmoid)
Silu = _simple("Silu", F.silu)
Swish = _simple("Swish", F.swish)
Tanhshrink = _simple("Tanhshrink", F.tanhshrink)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self.scale = scale
        self.alpha = alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Softplus(Layer):
    def __init__(self, beta=1, threshold=20, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold, self.value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold, self.value)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, self.training)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


SiLU = Silu   # upstream exposes both spellings


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs (paddle.nn.Softmax2D)."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


__all__ += ["SiLU", "Softmax2D"]
