"""Loss layers (python/paddle/nn/layer/loss.py parity, UNVERIFIED)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
           "BCEWithLogitsLoss", "HuberLoss", "KLDivLoss", "SmoothL1Loss",
           "MarginRankingLoss", "HingeEmbeddingLoss", "CosineEmbeddingLoss",
           "TripletMarginLoss", "MultiLabelSoftMarginLoss", "CTCLoss",
           "PoissonNLLLoss", "GaussianNLLLoss", "SigmoidFocalLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.huber_loss(input, label, reduction=self.reduction,
                            delta=self.delta)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-06, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p = margin, p
        self.epsilon, self.swap = epsilon, swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.epsilon = full, epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class SigmoidFocalLoss(Layer):
    def __init__(self, alpha=0.25, gamma=2.0, normalizer=None,
                 reduction="sum", name=None):
        super().__init__()
        self.alpha, self.gamma = alpha, gamma
        self.normalizer = normalizer
        self.reduction = reduction

    def forward(self, logit, label):
        return F.sigmoid_focal_loss(logit, label, self.normalizer,
                                    self.alpha, self.gamma, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, p=self.p,
                                   margin=self.margin, weight=self.weight,
                                   reduction=self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative,
            distance_function=self.distance_function, margin=self.margin,
            swap=self.swap, reduction=self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid over the default complete binary tree."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "HSigmoidLoss(is_custom=True) path tables are not "
                "supported; the default tree is used")
        from .. import initializer as I
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_classes - 1], attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.bias = None

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               bias=self.bias, path_table=path_table,
                               path_code=path_code)


__all__ += ["SoftMarginLoss", "MultiMarginLoss",
            "TripletMarginWithDistanceLoss", "HSigmoidLoss"]


class RNNTLoss(Layer):
    """RNN-T transducer loss layer over ``F.rnnt_loss``
    (paddle.nn.RNNTLoss parity)."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax layer (paddle.nn.AdaptiveLogSoftmaxWithLoss):
    owns the head + per-cluster down-projected tail weights, forwards to
    ``F.adaptive_log_softmax_with_loss``. Returns (output, loss)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        from .. import initializer as I
        cutoffs = list(cutoffs)
        if not cutoffs or cutoffs != sorted(set(cutoffs)) \
                or cutoffs[-1] >= n_classes:
            raise ValueError(
                f"cutoffs must be unique, increasing, < n_classes "
                f"({n_classes}); got {cutoffs}")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        head_size = cutoffs[0] + len(self.cutoffs) - 1
        self.head_weight = self.create_parameter(
            [in_features, head_size], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.head_bias = self.create_parameter(
            [head_size], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0)) if head_bias else None
        self.tail_weights = []
        for i in range(len(self.cutoffs) - 1):
            hsz = max(int(in_features // (div_value ** (i + 1))), 1)
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter(
                [in_features, hsz], attr=weight_attr,
                default_initializer=I.XavierNormal())
            out = self.create_parameter(
                [hsz, osz], attr=weight_attr,
                default_initializer=I.XavierNormal())
            # register under stable names so state_dict round-trips
            setattr(self, f"tail_proj_{i}", proj)
            setattr(self, f"tail_out_{i}", out)
            self.tail_weights.append([proj, out])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1], head_bias=self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probabilities (head + tails)."""
        import paddle_tpu as paddle
        head = paddle.matmul(input, self.head_weight)
        if self.head_bias is not None:
            head = head + self.head_bias
        head_lp = F.log_softmax(head, axis=-1)
        shortlist = head_lp[:, :self.cutoffs[0]]
        parts = [shortlist]
        n_tail = len(self.cutoffs) - 1
        for i in range(n_tail):
            cluster_lp = head_lp[:, self.cutoffs[0] + i]
            h = paddle.matmul(paddle.matmul(input, self.tail_weights[i][0]),
                              self.tail_weights[i][1])
            parts.append(F.log_softmax(h, axis=-1)
                         + cluster_lp.unsqueeze(-1))
        return paddle.concat(parts, axis=-1)

    def predict(self, input):
        return self.log_prob(input).argmax(axis=-1)


__all__ += ["RNNTLoss", "AdaptiveLogSoftmaxWithLoss"]
