"""``paddle.nn`` namespace (SURVEY.md §2.2: Layer system + ~150 layers)."""

from .layer.layers import Layer
from .layer.common import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from ..framework.core import Parameter  # noqa: F401

from ..framework.core import Tensor as _Tensor


class ClipGradByGlobalNorm:
    """Re-exported from optimizer (paddle exposes paddle.nn.ClipGradBy*)."""
    def __new__(cls, clip_norm=1.0, group_name="default_group",
                auto_skip_clip=False):
        from ..optimizer.clip import ClipGradByGlobalNorm as C
        return C(clip_norm, group_name, auto_skip_clip)


class ClipGradByNorm:
    def __new__(cls, clip_norm=1.0):
        from ..optimizer.clip import ClipGradByNorm as C
        return C(clip_norm)


class ClipGradByValue:
    def __new__(cls, max=1.0, min=None):
        from ..optimizer.clip import ClipGradByValue as C
        return C(max, min)


def utils_clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                          error_if_nonfinite=False):
    from ..optimizer.clip import clip_grad_norm_
    return clip_grad_norm_(parameters, max_norm, norm_type,
                           error_if_nonfinite)
