"""paddle.nn.utils — parameter utilities (upstream
``python/paddle/nn/utils/``, UNVERIFIED; see SURVEY.md provenance warning):
weight_norm / remove_weight_norm, spectral_norm, parameters_to_vector /
vector_to_parameters, clip_grad_norm_ / clip_grad_value_.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Parameter, Tensor, apply
from ...optimizer.clip import (clip_grad_norm_,  # noqa: F401
                               clip_grad_value_)

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def parameters_to_vector(parameters, name=None):
    """Flatten parameters into one 1-D tensor (differentiable concat)."""
    params = list(parameters)
    from ...ops.manipulation import concat, reshape
    return concat([reshape(p, [-1]) for p in params], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    """Scatter a flat vector back into the parameter list (in place)."""
    params = list(parameters)
    arr = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    sizes = []
    for p in params:
        n = 1
        for s in p.shape:
            n *= int(s)
        sizes.append(n)
    total = sum(sizes)
    if total != arr.shape[0]:
        # validate BEFORE mutating: a partial scatter would corrupt params
        raise ValueError(
            f"vector has {arr.shape[0]} elements but parameters need "
            f"{total}")
    offset = 0
    for p, n in zip(params, sizes):
        chunk = arr[offset:offset + n].reshape(tuple(int(s)
                                                     for s in p.shape))
        p.set_data(chunk.astype(p._data.dtype))
        offset += n


def _norm_except_dim(v, dim):
    """||v|| reduced over every axis except `dim` (paddle weight_norm
    semantics; dim=None or -1 -> single global norm)."""
    if dim is None or dim == -1:
        return jnp.sqrt(jnp.sum(jnp.square(v)))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    shape = [1] * v.ndim
    shape[dim] = v.shape[dim]
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes)).reshape(shape)


class _WeightNormHook:
    """Forward-pre-hook recomputing ``name = g * v / ||v||`` from the
    ``name_g`` / ``name_v`` parameters each call, so autograd flows into
    g and v (the tape records the reparameterization ops)."""

    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def compute(self, layer):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        dim = self.dim

        def fn(ga, va):
            return ga * va / jnp.maximum(_norm_except_dim(va, dim), 1e-12)

        w = apply(fn, g, v, name="weight_norm")
        object.__setattr__(layer, self.name, w)

    def __call__(self, layer, inputs):
        self.compute(layer)
        return None


def weight_norm(layer, name="weight", dim=0):
    """Apply weight normalization to a layer parameter
    (paddle.nn.utils.weight_norm): replaces ``name`` with ``name_g``
    (magnitude) and ``name_v`` (direction)."""
    if hasattr(layer, "_weight_norm_hooks") and \
            name in layer._weight_norm_hooks:
        raise RuntimeError(f"weight_norm already applied to {name!r}")
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f"{name!r} is not a Parameter of {type(layer)}")
    wd = w._data
    g0 = _norm_except_dim(wd, dim)
    v0 = wd
    del layer._parameters[name]
    setattr(layer, name + "_g", Parameter(g0, name=(w.name or name) + "_g"))
    setattr(layer, name + "_v", Parameter(v0, name=(w.name or name) + "_v"))
    hook = _WeightNormHook(name, dim)
    handle = layer.register_forward_pre_hook(hook)
    if not hasattr(layer, "_weight_norm_hooks"):
        object.__setattr__(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = (hook, handle)
    hook.compute(layer)  # materialize `name` for immediate use
    return layer


def remove_weight_norm(layer, name="weight"):
    """Undo weight_norm: fold g*v/||v|| back into a single parameter."""
    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"no weight_norm on parameter {name!r}")
    hook, handle = hooks.pop(name)
    handle.remove()
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    w = g._data * v._data / jnp.maximum(
        _norm_except_dim(v._data, hook.dim), 1e-12)
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    if name in layer.__dict__:
        object.__delattr__(layer, name)
    setattr(layer, name, Parameter(w, name=name))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Apply spectral normalization (power iteration) to a layer parameter
    — divides the weight by its largest singular value each forward."""
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f"{name!r} is not a Parameter of {type(layer)}")
    if dim is None:
        dim = 0
    wd = w._data
    mat = jnp.moveaxis(wd, dim, 0).reshape(wd.shape[dim], -1)
    import numpy as _np
    rng = _np.random.RandomState(0)
    u0 = jnp.asarray(rng.randn(mat.shape[0]).astype(_np.float32))
    u0 = u0 / jnp.maximum(jnp.linalg.norm(u0), eps)

    state = {"u": u0}

    def power_iter(m, u):
        v = None
        for _ in range(n_power_iterations):
            v = m.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = m @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        return u, v

    def hook(lyr, inputs):
        wp = getattr(lyr, name + "_orig")
        u_in = state["u"]

        def fn(wa):
            m = jnp.moveaxis(wa, dim, 0).reshape(wa.shape[dim], -1)
            u, v = power_iter(m, jax.lax.stop_gradient(u_in))
            sigma = u @ (m @ v)
            return wa / sigma

        wn = apply(fn, wp, name="spectral_norm")
        # persist the power-iteration vector across forwards (torch/paddle
        # semantics: sigma converges over calls even with 1 iteration).
        # Only outside a trace — a tracer leaking into `state` would poison
        # later compiled calls.
        from ...framework.core import trace_clean
        if trace_clean():
            m = jnp.moveaxis(wp._data, dim, 0).reshape(wp._data.shape[dim],
                                                       -1)
            u_new, _ = power_iter(m, u_in)
            state["u"] = u_new
        object.__setattr__(lyr, name, wn)
        return None

    del layer._parameters[name]
    setattr(layer, name + "_orig", Parameter(wd, name=(w.name or name)
                                             + "_orig"))
    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer
