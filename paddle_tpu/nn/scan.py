"""Scanned execution of a stack of structurally identical layers.

Why this exists (TPU-first design, SURVEY.md §7): a python loop over N
decoder layers unrolls into N copies of the layer's HLO. Measured on
v5e: the unrolled Llama step compiles to ~220 MB of TPU code and runs
~60x slower than ideal — generated-code size, not FLOPs or HBM, was the
bottleneck. Rolling the stack into ONE ``lax.scan`` over stacked weights
collapses code size to one layer body (measured: 3.4 MB, ~20x faster
end-to-end) and is also the natural place for per-layer
rematerialization (``jax.checkpoint`` on the scan body — the standard
TPU memory/compute trade).

The reference has no analogue (CUDA kernels are data, not code — code
size is a non-issue on GPU); this is a TPU-native architectural choice.

Works with the framework's tape: the whole scan is ONE differentiable
``apply`` op; jax reverse-mode differentiates through the scan,
re-binding the template layer's parameters to the per-iteration weight
slices exactly like the compiled-pipeline engine does
(distributed/fleet/meta_parallel/pipeline_parallel.py ``_body_apply``).

Constraints: layers must share parameter structure (shape/dtype, same
class); the carried activation must be shape/dtype-stable; layers must
be deterministic under the scan (no per-layer RNG — callers fall back
to the python loop when dropout is live).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.core import Tensor, apply, no_grad

__all__ = ["scan_layers", "can_scan"]


def can_scan(layers):
    """True iff the layer stack is scannable: >1 layers, identical
    class and parameter shapes/dtypes."""
    layers = list(layers)
    if len(layers) < 2:
        return False
    sig0 = None
    for l in layers:
        sig = (type(l), tuple((tuple(p.shape), str(p.dtype))
                              for p in l.parameters()))
        if sig0 is None:
            sig0 = sig
        elif sig != sig0:
            return False
    return len(sig0[1]) > 0


def scan_layers(layers, x, extra_inputs=(), remat=False):
    """Run ``x -> layers[L-1](...layers[0](x))`` as one lax.scan.

    layers: sequence of structurally identical Layers.
    x: Tensor carried through the stack (shape/dtype preserved).
    extra_inputs: Tensors passed unchanged to every layer after x
      (e.g. an attention mask).
    remat: rematerialize each layer in backward (per-layer activation
      checkpointing).
    """
    layers = list(layers)
    template = layers[0]
    tmpl_params = list(template.parameters())
    per_layer = [list(l.parameters()) for l in layers]
    n_leaves = len(tmpl_params)
    L = len(layers)
    n_extra = len(extra_inputs)

    def fn(h, *rest):
        extras = rest[:n_extra]
        leaves = rest[n_extra:]
        stacked = tuple(
            jnp.stack([leaves[g * n_leaves + i] for g in range(L)])
            for i in range(n_leaves))

        def body(carry, slices):
            originals = [(p, p._data) for p in tmpl_params]
            try:
                for p, a in zip(tmpl_params, slices):
                    p._data = a
                ins = [Tensor(carry)] + [Tensor(e) for e in extras]
                with no_grad():
                    out = template(*ins)
                out = out.jax() if isinstance(out, Tensor) else out
                return out, None
            finally:
                for p, a in originals:
                    p._data = a

        if remat:
            from ..incubate.recompute import checkpoint_with_policy
            body = checkpoint_with_policy(body)
        out, _ = lax.scan(body, h, stacked)
        return out

    flat = [p for lp in per_layer for p in lp]
    return apply(fn, x, *extra_inputs, *flat, name="scan_layers")
