"""Scanned execution of a stack of structurally identical layers.

Why this exists (TPU-first design, SURVEY.md §7): a python loop over N
decoder layers unrolls into N copies of the layer's HLO. Measured on
v5e: the unrolled Llama step compiles to ~220 MB of TPU code and runs
~60x slower than ideal — generated-code size, not FLOPs or HBM, was the
bottleneck. Rolling the stack into ONE ``lax.scan`` over stacked weights
collapses code size to one layer body (measured: 3.4 MB, ~20x faster
end-to-end) and is also the natural place for per-layer
rematerialization (``jax.checkpoint`` on the scan body — the standard
TPU memory/compute trade).

The reference has no analogue (CUDA kernels are data, not code — code
size is a non-issue on GPU); this is a TPU-native architectural choice.

Works with the framework's tape: the whole scan is ONE differentiable
``apply`` op; jax reverse-mode differentiates through the scan,
re-binding the template layer's parameters to the per-iteration weight
slices exactly like the compiled-pipeline engine does
(distributed/fleet/meta_parallel/pipeline_parallel.py ``_body_apply``).

Constraints: layers must share parameter structure (shape/dtype, same
class); the carried activation must be shape/dtype-stable; layers must
be deterministic under the scan (no per-layer RNG — callers fall back
to the python loop when dropout is live).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.core import Tensor, apply, no_grad

__all__ = ["scan_layers", "can_scan"]


def _log_decline(reason):
    # Declining the scan path is a 20-60x compiled-speed cliff (module
    # docstring) that used to be SILENT; route it through the trace
    # layer so user runs show WHY the stack unrolled (VERDICT r5 weak
    # #7). Deduped per reason: can_scan runs every forward.
    from ..profiler.trace import log_perf_event
    log_perf_event("scan/declined",
                   f"scan_layers declined ({reason}); falling back to the "
                   "unrolled per-layer path (much larger compiled "
                   "program)", once_key=("scan/declined", reason))


def can_scan(layers):
    """True iff the layer stack is scannable: >1 layers, identical
    class and parameter shapes/dtypes. Declines are logged at INFO on
    the ``paddle_tpu.perf`` logger (once per distinct reason)."""
    layers = list(layers)
    if len(layers) < 2:
        _log_decline(f"stack has {len(layers)} layer(s), need >= 2")
        return False
    sig0 = None
    for i, l in enumerate(layers):
        sig = (type(l), tuple((tuple(p.shape), str(p.dtype))
                              for p in l.parameters()))
        if sig0 is None:
            sig0 = sig
        elif sig != sig0:
            what = "class" if sig[0] is not sig0[0] else \
                "parameter shapes/dtypes"
            _log_decline(
                f"layer {i} ({type(l).__name__}) differs from layer 0 "
                f"({sig0[0].__name__}) in {what}")
            return False
    if not sig0[1]:
        _log_decline(f"layers ({sig0[0].__name__}) have no parameters")
        return False
    return True


def scan_layers(layers, x, extra_inputs=(), remat=False,
                full_save_interval=0):
    """Run ``x -> layers[L-1](...layers[0](x))`` as one lax.scan.

    layers: sequence of structurally identical Layers.
    x: Tensor carried through the stack (shape/dtype preserved).
    extra_inputs: Tensors passed unchanged to every layer after x
      (e.g. an attention mask).
    remat: rematerialize each layer in backward (per-layer activation
      checkpointing).
    full_save_interval (fs, with remat): the remat DOSE under the scan —
      every fs-th layer keeps its activations whole instead of
      recomputing, same knob as the unrolled path. Realized by scanning
      over L/fs GROUPS of fs layers: the group body runs fs layers with
      the first fs-1 under jax.checkpoint and the group-last saved
      (per-iteration save structure must be static, so the dose is the
      group shape, not a per-iteration predicate). Requires L % fs == 0;
      otherwise falls back to fs=0 with a warning. ``None`` (instead of
      an int) consults the autotuner cache ("scan_remat" surface, keyed
      by stack depth) and falls back to 0.
    """
    layers = list(layers)
    template = layers[0]
    tmpl_params = list(template.parameters())
    per_layer = [list(l.parameters()) for l in layers]
    n_leaves = len(tmpl_params)
    L = len(layers)
    n_extra = len(extra_inputs)
    if full_save_interval is None:
        from ..tuner import lookup
        cfg = lookup("scan_remat", {"L": L}) or {}
        full_save_interval = int(cfg.get("full_save_interval", 0))
    fs = max(int(full_save_interval or 0), 0)  # same clamp as unrolled
    if fs and not remat:
        fs = 0
    if fs == 1:
        # same knob meaning as the unrolled path: every layer saves
        # whole = no remat at all
        remat, fs = False, 0
    if fs and L % fs:
        import warnings
        warnings.warn(
            f"scan_layers: full_save_interval={fs} must tile "
            f"num_layers ({L}); running without the dose",
            stacklevel=2)
        from ..profiler.trace import log_perf_event
        log_perf_event(
            "scan/full_save_interval_dropped",
            f"full_save_interval={fs} does not tile num_layers={L}; "
            "remat dose dropped (every layer recomputes — slower "
            "backward than configured)",
            once_key=("scan/fs_dropped", fs, L))
        fs = 0

    def fn(h, *rest):
        extras = rest[:n_extra]
        leaves = rest[n_extra:]

        def one_layer(carry, slices):
            originals = [(p, p._data) for p in tmpl_params]
            try:
                for p, a in zip(tmpl_params, slices):
                    p._data = a
                ins = [Tensor(carry)] + [Tensor(e) for e in extras]
                with no_grad():
                    out = template(*ins)
                return out.jax() if isinstance(out, Tensor) else out
            finally:
                for p, a in originals:
                    p._data = a

        stacked = tuple(
            jnp.stack([leaves[g * n_leaves + i] for g in range(L)])
            for i in range(n_leaves))

        if fs:
            # scan over L/fs GROUPS ([G, fs, ...] = a reshape of the
            # [L, ...] stack); group body: fs-1 rematted + 1 saved
            G = L // fs
            stacked = tuple(s.reshape((G, fs) + s.shape[1:])
                            for s in stacked)
            from ..incubate.recompute import checkpoint_with_policy
            ck_layer = checkpoint_with_policy(one_layer)

            def body(carry, slices):
                h = carry
                for j in range(fs):
                    sl = tuple(s[j] for s in slices)
                    h = (ck_layer if j < fs - 1 else one_layer)(h, sl)
                return h, None

            out, _ = lax.scan(body, h, stacked)
            return out

        def body(carry, slices):
            return one_layer(carry, slices), None

        if remat:
            from ..incubate.recompute import checkpoint_with_policy
            body = checkpoint_with_policy(body)
        out, _ = lax.scan(body, h, stacked)
        return out

    flat = [p for lp in per_layer for p in lp]
    return apply(fn, x, *extra_inputs, *flat, name="scan_layers")


# -- tunable surface ---------------------------------------------------------
# The remat dose is a memory/compute trade the roofline cannot rank
# (no cost_fn — the winner depends on whether the config fits HBM at
# all), so trials need a model-level vehicle and there is no automated
# one yet: record a winner by pinning it
# (incubate.autotune.set_config(kernel={'configs': {'scan_remat':
# ...}})) or writing the cache entry from a manual A/B. Registered
# anyway so the grid/validity rule, the consult path
# (full_save_interval=None) and any recorded winner live in the same
# registry as the kernel tiles.

def _register_scan_surface():
    from ..tuner.surface import TunableSurface, register_surface

    def _candidates(shape):
        L = int(shape.get("L", 0))
        doses = [0] + [fs for fs in (1, 2, 3, 4, 6, 8)
                       if L and L % fs == 0]
        return [{"full_save_interval": fs} for fs in doses]

    def _is_valid(config, shape):
        fs = int(config["full_save_interval"])
        L = int(shape.get("L", 0))
        return fs == 0 or (L > 0 and L % fs == 0)

    register_surface(TunableSurface(
        name="scan_remat",
        params=("full_save_interval",),
        default={"full_save_interval": 0},
        candidates=_candidates,
        is_valid=_is_valid,
        describe="Remat dose under scan_layers: every fs-th layer "
                 "saves activations whole (0 = every layer recomputes, "
                 "1 = no remat). Shape key: stack depth L; fs must "
                 "tile L."))


_register_scan_surface()
