"""``paddle.nn.quant`` — weight-only quantization for LLM serving
(reference: ``python/paddle/nn/quant/quantized_linear.py`` —
weight_quantize/weight_only_linear/llm_int8_linear; UNVERIFIED, mount
empty).

TPU-native notes: the reference packs weights into cutlass-friendly
layouts and runs dedicated GPU kernels. Here the quantized weight is
plain row-major int8 ([in, out], values in int8 or int4 range) and
``weight_only_linear`` computes ``(x @ w_q) * scale`` — the dequant
rides AFTER the matmul as a per-out-channel rescale, which XLA fuses
into the matmul epilogue (the memory win — int8 weights in HBM — is
what weight-only quantization is for; the MXU computes in bf16 either
way). llm_int8's outlier decomposition (threshold-split mixed
precision) is a GPU-kernel trick; on TPU the same epilogue form is
used and the threshold is accepted for API parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]

_INT_RANGE = {"weight_only_int8": 127.0, "llm.int8": 127.0,
              "weight_only_int4": 7.0}
# clip bounds: int8 stays symmetric ([-127, 127], the reference skips
# -128), int4 clips to the FULL asymmetric two's-complement range
# [-8, 7] like the reference kernels (advisor r5) — absmax/7 scaling
# never ROUNDS to -8, but pre-quantized checkpoints and group-wise
# paths carry it, and re-clipping to -7 would corrupt those values
_INT_CLIP = {"weight_only_int8": (-127.0, 127.0),
             "llm.int8": (-127.0, 127.0),
             "weight_only_int4": (-8.0, 7.0)}


def weight_quantize(x, algo="weight_only_int8", arch=None,
                    group_size=-1):
    """Per-out-channel absmax quantization: x [in, out] float ->
    (w_q int8 [in, out], scale float32 [out]). int4 values live in the
    full asymmetric range [-8, 7] stored one-per-int8 (the reference
    nibble-packs; the layout is backend-private there too, so parity is
    (quant, scale) semantics, not bytes)."""
    if algo not in _INT_RANGE:
        raise ValueError(f"unknown weight_quantize algo {algo!r}")
    r = _INT_RANGE[algo]
    lo, hi = _INT_CLIP[algo]

    def fn(w):
        wf = w.astype(jnp.float32)
        if group_size and group_size > 0:
            k = wf.shape[0]
            if k % group_size:
                raise ValueError(
                    f"in_features {k} not divisible by group_size "
                    f"{group_size}")
            g = wf.reshape(k // group_size, group_size, -1)
            scale = jnp.max(jnp.abs(g), axis=1) / r   # [groups, out]
            q = jnp.clip(jnp.round(g / jnp.maximum(scale, 1e-8)[:, None]),
                         lo, hi).astype(jnp.int8)
            return q.reshape(wf.shape), scale
        scale = jnp.max(jnp.abs(wf), axis=0) / r      # [out]
        q = jnp.clip(jnp.round(wf / jnp.maximum(scale, 1e-8)),
                     lo, hi).astype(jnp.int8)
        return q, scale

    return apply(fn, x, n_outputs=2, differentiable=False,
                 name="weight_quantize")


def _dequant(q, s):
    """Shared dequant math (per-channel [out] or group-wise
    [groups, out] scales) — ONE home for the group reshape/rescale."""
    if s.ndim == 2:
        g = q.reshape(s.shape[0], -1, q.shape[-1])
        return (g.astype(jnp.float32) * s[:, None, :]).reshape(q.shape)
    return q.astype(jnp.float32) * s


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float16", group_size=-1):
    def fn(q, s):
        return _dequant(q, s).astype(out_dtype)

    return apply(fn, x, scale, differentiable=False,
                 name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) (+ bias) with the dequant folded into
    the matmul epilogue for per-channel scales."""
    args = [x, weight] + ([weight_scale] if weight_scale is not None
                          else []) + ([bias] if bias is not None else [])

    def fn(xx, w, *rest):
        i = 0
        s = None
        if weight_scale is not None:
            s = rest[i]
            i += 1
        b = rest[i] if bias is not None else None
        cd = xx.dtype
        if s is not None and s.ndim == 2:
            # group-wise scales can't ride the epilogue: dequantize
            y = jnp.matmul(xx.astype(jnp.float32),
                           _dequant(w, s)).astype(cd)
        else:
            y = jnp.matmul(xx, w.astype(cd))
            if s is not None:
                y = (y.astype(jnp.float32) * s).astype(cd)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    return apply(fn, *args, name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """API parity for the LLM.int8 path — on TPU the epilogue-scaled
    int8 matmul serves both (threshold accepted, not needed: no
    outlier-split kernels here)."""
    return weight_only_linear(x, weight, bias=bias,
                              weight_scale=weight_scale,
                              weight_dtype="int8")
