"""``paddle.nn.quant`` — weight-only quantization for LLM serving
(reference: ``python/paddle/nn/quant/quantized_linear.py`` —
weight_quantize/weight_only_linear/llm_int8_linear; UNVERIFIED, mount
empty).

TPU-native notes: the reference packs weights into cutlass-friendly
layouts and runs dedicated GPU kernels. Here the quantized weight is
plain row-major int8 ([in, out], values in int8 or int4 range) and
``weight_only_linear`` computes ``(x @ w_q) * scale`` — the dequant
rides AFTER the matmul as a per-out-channel rescale, which XLA fuses
into the matmul epilogue (the memory win — int8 weights in HBM — is
what weight-only quantization is for; the MXU computes in bf16 either
way). llm_int8's outlier decomposition (threshold-split mixed
precision) is a GPU-kernel trick; on TPU the same epilogue form is
used and the threshold is accepted for API parity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...profiler import metrics as _pmetrics
from ..layer.layers import Layer as _Layer

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "WeightOnlyLinear", "quantize_for_serving"]

# -- weight-only serving quantization: HBM footprint gauges (ISSUE 20)
_pmetrics.declare("quant/weight_layers", "gauge",
                  "projection layers converted to weight-only "
                  "quantized form by quantize_for_serving")
_pmetrics.declare("quant/weight_bytes", "gauge",
                  "bytes of quantized projection weights resident in "
                  "HBM (int8 codes + f32 scales; int4 nibble-packed)")
_pmetrics.declare("quant/weight_bytes_saved", "gauge",
                  "HBM bytes saved vs the original full-precision "
                  "projection weights (the 2-4x weight capacity win)")

_INT_RANGE = {"weight_only_int8": 127.0, "llm.int8": 127.0,
              "weight_only_int4": 7.0}
# clip bounds: int8 stays symmetric ([-127, 127], the reference skips
# -128), int4 clips to the FULL asymmetric two's-complement range
# [-8, 7] like the reference kernels (advisor r5) — absmax/7 scaling
# never ROUNDS to -8, but pre-quantized checkpoints and group-wise
# paths carry it, and re-clipping to -7 would corrupt those values
_INT_CLIP = {"weight_only_int8": (-127.0, 127.0),
             "llm.int8": (-127.0, 127.0),
             "weight_only_int4": (-8.0, 7.0)}


def weight_quantize(x, algo="weight_only_int8", arch=None,
                    group_size=-1):
    """Per-out-channel absmax quantization: x [in, out] float ->
    (w_q int8 [in, out], scale float32 [out]). int4 values live in the
    full asymmetric range [-8, 7] stored one-per-int8 (the reference
    nibble-packs; the layout is backend-private there too, so parity is
    (quant, scale) semantics, not bytes)."""
    if algo not in _INT_RANGE:
        raise ValueError(f"unknown weight_quantize algo {algo!r}")
    r = _INT_RANGE[algo]
    lo, hi = _INT_CLIP[algo]

    def fn(w):
        wf = w.astype(jnp.float32)
        if group_size and group_size > 0:
            k = wf.shape[0]
            if k % group_size:
                raise ValueError(
                    f"in_features {k} not divisible by group_size "
                    f"{group_size}")
            g = wf.reshape(k // group_size, group_size, -1)
            scale = jnp.max(jnp.abs(g), axis=1) / r   # [groups, out]
            q = jnp.clip(jnp.round(g / jnp.maximum(scale, 1e-8)[:, None]),
                         lo, hi).astype(jnp.int8)
            return q.reshape(wf.shape), scale
        scale = jnp.max(jnp.abs(wf), axis=0) / r      # [out]
        q = jnp.clip(jnp.round(wf / jnp.maximum(scale, 1e-8)),
                     lo, hi).astype(jnp.int8)
        return q, scale

    return apply(fn, x, n_outputs=2, differentiable=False,
                 name="weight_quantize")


def _dequant(q, s):
    """Shared dequant math (per-channel [out] or group-wise
    [groups, out] scales) — ONE home for the group reshape/rescale."""
    if s.ndim == 2:
        g = q.reshape(s.shape[0], -1, q.shape[-1])
        return (g.astype(jnp.float32) * s[:, None, :]).reshape(q.shape)
    return q.astype(jnp.float32) * s


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float16", group_size=-1):
    def fn(q, s):
        return _dequant(q, s).astype(out_dtype)

    return apply(fn, x, scale, differentiable=False,
                 name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) (+ bias) with the dequant folded into
    the matmul epilogue for per-channel scales."""
    args = [x, weight] + ([weight_scale] if weight_scale is not None
                          else []) + ([bias] if bias is not None else [])

    def fn(xx, w, *rest):
        i = 0
        s = None
        if weight_scale is not None:
            s = rest[i]
            i += 1
        b = rest[i] if bias is not None else None
        cd = xx.dtype
        if s is not None and s.ndim == 2:
            # group-wise scales can't ride the epilogue: dequantize
            y = jnp.matmul(xx.astype(jnp.float32),
                           _dequant(w, s)).astype(cd)
        else:
            y = jnp.matmul(xx, w.astype(cd))
            if s is not None:
                y = (y.astype(jnp.float32) * s).astype(cd)
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    return apply(fn, *args, name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """API parity for the LLM.int8 path — on TPU the epilogue-scaled
    int8 matmul serves both (threshold accepted, not needed: no
    outlier-split kernels here)."""
    return weight_only_linear(x, weight, bias=bias,
                              weight_scale=weight_scale,
                              weight_dtype="int8")


# -- weight-only serving layers (ISSUE 20) -----------------------------------

def _pack_int4(q):
    """int8 codes in [-8, 7], [in, out] -> nibble-packed int8
    [ceil(in/2), out]: even row in the low nibble, odd row in the high
    nibble (odd in_features pads a zero row)."""
    import numpy as np
    q = np.asarray(q, np.int8)
    if q.shape[0] % 2:
        q = np.concatenate([q, np.zeros((1, q.shape[1]), np.int8)])
    lo, hi = q[0::2], q[1::2]
    return ((lo & 0xF) | (hi << 4)).astype(np.int8)


def _unpack_int4(p, in_features):
    """Inverse of :func:`_pack_int4` (jnp, trace-safe): sign-extend
    both nibbles via arithmetic shifts."""
    lo = jnp.right_shift(jnp.left_shift(p, 4), 4)
    hi = jnp.right_shift(p, 4)
    w = jnp.stack([lo, hi], axis=1).reshape(-1, p.shape[-1])
    return w[:in_features]


def _wol_forward(x, w_q, scale, bias, algo, in_features):
    """One fused apply: (unpack if int4) -> matmul -> epilogue scale
    (+ bias) — the weight_only_linear math with the int4 unpack folded
    into the same traced fn so the unpacked int8 never round-trips."""
    args = [x, w_q, scale] + ([bias] if bias is not None else [])

    def fn(xx, w, s, *rest):
        if algo == "weight_only_int4":
            w = _unpack_int4(w, in_features)
        cd = xx.dtype
        y = jnp.matmul(xx, w.astype(cd))
        y = (y.astype(jnp.float32) * s).astype(cd)
        if rest:
            y = y + rest[0].astype(cd)
        return y

    return apply(fn, *args, differentiable=False,
                 name="weight_only_linear")


class WeightOnlyLinear(_Layer):
    """Serving-time replacement for a Linear-family projection: int8
    (or nibble-packed int4) weight codes + per-out-channel f32 scales
    live in HBM as BUFFERS (2-4x fewer weight bytes), and the forward
    runs the ``weight_only_linear`` dequant-in-matmul epilogue. Built
    once at load by :func:`quantize_for_serving`; inference-only (the
    quantized weight is not a trainable Parameter)."""

    def __init__(self, weight, bias=None, algo="weight_only_int8"):
        super().__init__()
        if algo not in ("weight_only_int8", "weight_only_int4"):
            raise ValueError(
                f"unsupported serving weight_quant algo {algo!r}")
        w = weight._data if isinstance(weight, Tensor) else \
            jnp.asarray(weight)
        self._algo = algo
        self.in_features = int(w.shape[0])
        self.out_features = int(w.shape[1])
        q, s = weight_quantize(Tensor(w), algo=algo)
        if algo == "weight_only_int4":
            q = Tensor(jnp.asarray(_pack_int4(q._data)))
        self.register_buffer("weight_q", q)
        self.register_buffer("weight_scale", s)
        if bias is not None:
            b = bias if isinstance(bias, Tensor) else \
                Tensor(jnp.asarray(bias))
            self.register_buffer("bias", b)
        else:
            self.bias = None

    def forward(self, x):
        return _wol_forward(x, self.weight_q, self.weight_scale,
                            self.bias, self._algo, self.in_features)

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"algo={self._algo}")


#: projection names the serving path quantizes — the big matmuls of
#: the Llama/Qwen2 family (qkv/o/gate/up/down + LM head) and GPT2's
#: fused equivalents. Norms/embeddings stay full precision.
_QUANT_TARGETS = frozenset({
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj", "lm_head",
    "c_attn", "c_proj", "c_fc",
})


def quantize_for_serving(model, algo=None, targets=None):
    """Convert a model's big projections to :class:`WeightOnlyLinear`
    in place (once, at load): walks every sublayer, replaces children
    whose name is in ``targets`` (default :data:`_QUANT_TARGETS`) and
    whose type is Linear-family, and reports the HBM weight-byte
    delta on the ``quant/*`` gauges. ``algo`` defaults to
    ``model.config.weight_quant``. Idempotent — already-converted
    layers are skipped. A tied-embedding model with ``lm_head=None``
    simply has no lm_head child to convert (the embedding matmul stays
    full precision, matching the reference weight-only scope)."""
    if algo is None:
        algo = getattr(getattr(model, "config", None), "weight_quant",
                       None)
    if not algo:
        return {"layers": 0, "bytes": 0, "bytes_saved": 0}
    from ..layer.common import Linear
    try:
        from ...distributed.parallel_layers import (
            ColumnParallelLinear, RowParallelLinear)
        linear_types = (Linear, ColumnParallelLinear, RowParallelLinear)
    except Exception:      # pragma: no cover — distributed is baked in
        linear_types = (Linear,)
    names = frozenset(targets) if targets is not None else _QUANT_TARGETS
    import numpy as np
    converted = q_bytes = saved = 0
    parents = [model] + [lyr for _, lyr in model.named_sublayers()]
    for parent in parents:
        for cname, child in list(parent.named_children()):
            if cname not in names or not isinstance(child, linear_types):
                continue
            w = child.weight._data
            bias = getattr(child, "bias", None)
            wol = WeightOnlyLinear(Tensor(w), bias=bias, algo=algo)
            setattr(parent, cname, wol)
            orig = int(np.prod(w.shape)) * w.dtype.itemsize
            new = (wol.weight_q._data.nbytes
                   + wol.weight_scale._data.nbytes)
            converted += 1
            q_bytes += new
            saved += orig - new
    reg = _pmetrics.get_registry()
    reg.gauge("quant/weight_layers").set(converted)
    reg.gauge("quant/weight_bytes").set(q_bytes)
    reg.gauge("quant/weight_bytes_saved").set(saved)
    return {"layers": converted, "bytes": q_bytes, "bytes_saved": saved}
