"""Weight initializers — ``paddle.nn.initializer`` parity (UNVERIFIED path
python/paddle/nn/initializer/).  An initializer is a callable invoked with
(shape, dtype) -> jax array; ``Layer.create_parameter`` drives it."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, to_jax_dtype
from ...framework import random as framework_random

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Orthogonal", "Dirac", "Bilinear", "calculate_gain",
           "set_global_initializer"]


def _key():
    return framework_random.default_generator.next_key()


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle Linear weight is [in, out]
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    # conv weight [out_c, in_c/groups, *k]
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity: str, param=None) -> float:
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "selu": 3.0 / 4.0}
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    return gains.get(nonlinearity, 1.0)


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        d = to_jax_dtype(dtype)
        sample_dtype = d if jnp.issubdtype(d, jnp.floating) else jnp.float32
        return (self.mean + self.std *
                jax.random.normal(_key(), shape, jnp.float32)).astype(d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        d = to_jax_dtype(dtype)
        lo = (self.a - self.mean) / self.std if self.std else -2.0
        hi = (self.b - self.mean) / self.std if self.std else 2.0
        out = jax.random.truncated_normal(_key(), lo, hi, shape, jnp.float32)
        return (self.mean + self.std * out).astype(d)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        d = to_jax_dtype(dtype)
        return jax.random.uniform(_key(), shape, jnp.float32, self.low,
                                  self.high).astype(d)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return Normal(0.0, std)(shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._data
        arr = jnp.asarray(v, dtype=to_jax_dtype(dtype))
        return arr.reshape(shape)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype):
        init = jax.nn.initializers.orthogonal(scale=self.gain)
        return init(_key(), shape, to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype):
        w = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        per = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per, ic)):
                idx = (g * per + i, i) + tuple(s // 2 for s in shape[2:])
                w[idx] = 1.0
        return jnp.asarray(w, dtype=to_jax_dtype(dtype))


class Bilinear(Initializer):
    """Bilinear-upsampling kernel for transposed-conv weights
    (paddle.nn.initializer.Bilinear): every (out, in) channel pair gets
    the same separable triangle kernel, so the layer starts as bilinear
    interpolation."""

    def __init__(self, name=None):
        pass

    def __call__(self, shape, dtype):
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError(
                f"Bilinear initializer needs a 4-D conv weight, got "
                f"{shape}")
        kh, kw = shape[2], shape[3]
        f = math.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        xs = np.arange(kw, dtype=np.float64)
        ys = np.arange(kh, dtype=np.float64)
        kern = np.outer(1 - np.abs(ys / f - c), 1 - np.abs(xs / f - c))
        w = np.broadcast_to(kern, shape)
        return jnp.asarray(w, dtype=to_jax_dtype(dtype))


_global_weight_init: Initializer | None = None
_global_bias_init: Initializer | None = None


def set_global_initializer(weight_init, bias_init=None) -> None:
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def global_initializer(is_bias: bool):
    return _global_bias_init if is_bias else _global_weight_init
