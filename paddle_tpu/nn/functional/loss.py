"""Loss functionals (python/paddle/nn/functional/loss.py parity,
UNVERIFIED)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...ops.common import as_tensor

__all__ = ["cross_entropy", "huber_loss",
           "softmax_with_cross_entropy", "nll_loss",
           "mse_loss", "l1_loss", "smooth_l1_loss", "binary_cross_entropy",
           "binary_cross_entropy_with_logits", "kl_div", "margin_ranking_loss",
           "hinge_embedding_loss", "cosine_embedding_loss", "ctc_loss",
           "triplet_margin_loss", "multi_label_soft_margin_loss",
           "square_error_cost", "log_loss", "sigmoid_focal_loss",
           "poisson_nll_loss", "gaussian_nll_loss", "dice_loss"]


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    input, label = as_tensor(input), as_tensor(label)
    args = [input, label]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(logits, lab, *w):
        lf = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) \
            if use_softmax else jnp.log(jnp.maximum(
                logits.astype(jnp.float32), 1e-38))
        n_classes = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim and
                          lab.shape[axis] == n_classes and
                          jnp.issubdtype(lab.dtype, jnp.floating)):
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + \
                    label_smoothing / n_classes
            loss = -jnp.sum(soft * lf, axis=axis)
            mask = None
        else:
            idx = lab
            if idx.ndim == logits.ndim:  # [..., 1] hard labels
                idx = jnp.squeeze(idx, axis=axis)
            idx = idx.astype(jnp.int32)
            mask = (idx != ignore_index)
            safe = jnp.where(mask, idx, 0)
            if label_smoothing > 0:
                oh = jax.nn.one_hot(safe, n_classes, axis=axis,
                                    dtype=jnp.float32)
                soft = oh * (1 - label_smoothing) + \
                    label_smoothing / n_classes
                loss = -jnp.sum(soft * lf, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lf, jnp.expand_dims(safe, axis), axis=axis)
                loss = jnp.squeeze(loss, axis=axis)
            if w:
                cw = jnp.take(w[0].astype(jnp.float32), safe)
                loss = loss * cw
            loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            if mask is not None:
                if w:
                    cw = jnp.take(w[0].astype(jnp.float32),
                                  jnp.where(mask, idx, 0)) * mask
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(cw), 1e-12)
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(mask.astype(jnp.float32)), 1.0)
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(fn, *args, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as softmax_fn
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input, label = as_tensor(input), as_tensor(label)
    args = [input, label]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(lp, lab, *w):
        idx = lab.astype(jnp.int32)
        mask = (idx != ignore_index)
        safe = jnp.where(mask, idx, 0)
        loss = -jnp.take_along_axis(lp, safe[:, None] if lp.ndim == 2
                                    else jnp.expand_dims(safe, 1), axis=1)
        loss = jnp.squeeze(loss, axis=1)
        cw = None
        if w:
            cw = jnp.take(w[0], safe)
            loss = loss * cw
        loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(cw * mask) if cw is not None else \
                jnp.sum(mask.astype(loss.dtype))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(fn, *args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction),
                 as_tensor(input), as_tensor(label), name="mse_loss")


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), as_tensor(input),
                 as_tensor(label), name="square_error_cost")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 as_tensor(input), as_tensor(label), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        val = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label),
                 name="smooth_l1_loss")


def huber_loss(input, label, reduction="mean", delta=1.0, name=None):
    """Huber loss (quadratic below ``delta``, linear above) — unlike
    smooth_l1, the quadratic region is NOT rescaled by 1/delta."""
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        val = jnp.where(ad <= delta, 0.5 * d * d,
                        delta * (ad - 0.5 * delta))
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label),
                 name="huber_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = [as_tensor(input), as_tensor(label)]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        val = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            val = val * w[0]
        return _reduce(val, reduction)
    return apply(fn, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    args = [as_tensor(logit), as_tensor(label)]
    if weight is not None:
        args.append(as_tensor(weight))
    if pos_weight is not None:
        args.append(as_tensor(pos_weight))

    def fn(x, y, *rest):
        i = 0
        w = pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]
        # stable: max(x,0) - x*y + log(1+exp(-|x|)); with pos_weight:
        log_sig_x = jax.nn.log_sigmoid(x)
        log_sig_nx = jax.nn.log_sigmoid(-x)
        if pw is not None:
            val = -(pw * y * log_sig_x + (1 - y) * log_sig_nx)
        else:
            val = -(y * log_sig_x + (1 - y) * log_sig_nx)
        if w is not None:
            val = val * w
        return _reduce(val, reduction)
    return apply(fn, *args, name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, y):
        if log_target:
            val = jnp.exp(y) * (y - lp)
        else:
            val = y * (jnp.log(jnp.maximum(y, 1e-38)) - lp)
        if reduction == "batchmean":
            return jnp.sum(val) / lp.shape[0]
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def fn(a, b, y):
        val = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(other), as_tensor(label),
                 name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def fn(a, y):
        val = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label),
                 name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        val = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input1), as_tensor(input2), as_tensor(label),
                 name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-06, swap=False, reduction="mean",
                        name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, -1) ** (1 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        val = jnp.maximum(0.0, d_ap - d_an + margin)
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(positive),
                 as_tensor(negative), name="triplet_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    args = [as_tensor(input), as_tensor(label)]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(x, y, *w):
        val = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        val = jnp.mean(val, -1)
        if w:
            val = val * w[0]
        return _reduce(val, reduction)
    return apply(fn, *args, name="multi_label_soft_margin_loss")


def log_loss(input, label, epsilon=0.0001, name=None):
    def fn(p, y):
        return -(y * jnp.log(p + epsilon) +
                 (1 - y) * jnp.log(1 - p + epsilon))
    return apply(fn, as_tensor(input), as_tensor(label), name="log_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = [as_tensor(logit), as_tensor(label)]
    if normalizer is not None:
        args.append(as_tensor(normalizer))

    def fn(x, y, *nm):
        p = jax.nn.sigmoid(x)
        ce = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        val = a_t * ((1 - p_t) ** gamma) * ce
        if nm:
            val = val / nm[0]
        return _reduce(val, reduction)
    return apply(fn, *args, name="sigmoid_focal_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(x, y):
        if log_input:
            val = jnp.exp(x) - y * x
        else:
            val = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + (y == 0)) - y + \
                0.5 * jnp.log(2 * jnp.pi * jnp.maximum(y, 1.0))
            val = val + jnp.where(y > 1, stirling, 0.0)
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label),
                 name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        val = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            val = val + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, var.dtype))
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label), as_tensor(variance),
                 name="gaussian_nll_loss")


def dice_loss(input, label, epsilon=1e-05, name=None):
    def fn(p, y):
        yf = jax.nn.one_hot(jnp.squeeze(y, -1), p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * yf, axis=tuple(range(1, p.ndim)))
        union = jnp.sum(p, axis=tuple(range(1, p.ndim))) + \
            jnp.sum(yf, axis=tuple(range(1, p.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply(fn, as_tensor(input), as_tensor(label), name="dice_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    # log_probs: [T, N, C] (paddle layout)
    lp = as_tensor(log_probs)
    lab = as_tensor(labels)
    il = as_tensor(input_lengths)
    ll = as_tensor(label_lengths)

    def fn(logp, ys, in_len, lab_len):
        logp = jnp.transpose(logp, (1, 0, 2))  # [N, T, C]
        logp = jax.nn.log_softmax(logp, -1)
        N, T, C = logp.shape
        S = ys.shape[1]
        # classic alpha recursion over extended label seq with blanks
        ext = jnp.full((N, 2 * S + 1), blank, dtype=ys.dtype)
        ext = ext.at[:, 1::2].set(ys)
        L = 2 * lab_len + 1

        def get(logp_t, idx):
            return jnp.take_along_axis(logp_t, idx, axis=-1)

        neg_inf = -1e30
        alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
        first_lab = get(logp[:, 0], ext[:, 1:2])[:, 0]
        alpha0 = alpha0.at[:, 1].set(jnp.where(S > 0, first_lab, neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), dtype=bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, logp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            m = jnp.maximum(jnp.maximum(a_prev, a_shift1), a_shift2)
            summed = m + jnp.log(
                jnp.exp(a_prev - m) + jnp.exp(a_shift1 - m) +
                jnp.exp(a_shift2 - m) + 1e-38)
            emit = get(logp_t, ext)
            return summed + emit, None

        def scan_fn(alpha, t):
            new_alpha, _ = step(alpha, logp[:, t])
            keep = (t < in_len)[:, None]
            return jnp.where(keep, new_alpha, alpha), None

        alpha, _ = jax.lax.scan(scan_fn, alpha0, jnp.arange(1, T))
        idx_last = (L - 1)[:, None]
        idx_prev = jnp.maximum(L - 2, 0)[:, None]
        a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0]
        m = jnp.maximum(a_last, a_prev)
        ll_prob = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
        loss = -ll_prob
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len, 1))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(fn, lp, lab, il, ll, name="ctc_loss")


# ---- round-2 loss breadth --------------------------------------------------

def soft_margin_loss(input, label, reduction="mean", name=None):
    """log(1 + exp(-label * input)) (paddle soft_margin_loss);
    logaddexp form for overflow stability at large margins."""
    def fn(x, y):
        return _reduce(jnp.logaddexp(0.0, -y.astype(x.dtype) * x),
                       reduction)
    return apply(fn, as_tensor(input), as_tensor(label),
                 name="soft_margin_loss")


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Multi-class hinge loss over logits [N, C]."""
    args = [as_tensor(input), as_tensor(label)]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(x, y, *w):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(margin - correct + x, 0.0) ** p
        if w:
            m = m * w[0][y][:, None]
        mask = 1.0 - jax.nn.one_hot(y, c, dtype=x.dtype)
        per = jnp.sum(m * mask, axis=1) / c
        return _reduce(per, reduction)
    return apply(fn, *args, name="multi_margin_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (paddle.nn.functional.npair_loss)."""
    def fn(a, p, y):
        sim = a @ p.T
        eq = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = eq / jnp.sum(eq, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1))
                        + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return ce + reg
    return apply(fn, as_tensor(anchor), as_tensor(positive),
                 as_tensor(labels), name="npair_loss")


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    a, p, n = as_tensor(input), as_tensor(positive), as_tensor(negative)

    def euclid(u, v):
        return jnp.sqrt(jnp.sum((u - v) ** 2, axis=-1) + 1e-12)

    def fn(x, pp, nn):
        if distance_function is not None:
            dp = distance_function(Tensor(x), Tensor(pp)).jax()
            dn = distance_function(Tensor(x), Tensor(nn)).jax()
            if swap:
                dpn = distance_function(Tensor(pp), Tensor(nn)).jax()
                dn = jnp.minimum(dn, dpn)
        else:
            dp, dn = euclid(x, pp), euclid(x, nn)
            if swap:
                dn = jnp.minimum(dn, euclid(pp, nn))
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply(fn, a, p, n, name="triplet_margin_with_distance_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over the default complete binary tree
    (paddle.nn.functional.hsigmoid_loss; custom path tables unsupported —
    the default tree is what the reference builds when none is given)."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError(
            "hsigmoid_loss with a custom path_table/path_code tree is not "
            "supported; use the default complete binary tree")
    import numpy as _np
    C = int(num_classes)
    depth = int(_np.ceil(_np.log2(C))) if C > 1 else 1
    # default tree: internal node ids 0..C-2; leaf for class c follows the
    # binary expansion of (c + C - 1) from the root (heap layout)
    codes = _np.zeros((C, depth), _np.float32)
    tables = _np.zeros((C, depth), _np.int64)
    valid = _np.zeros((C, depth), _np.float32)
    for c in range(C):
        node = c + C - 1          # heap index of the leaf
        path = []
        while node > 0:
            parent = (node - 1) // 2
            path.append((parent, float(node == 2 * parent + 2)))
            node = parent
        for d, (pnode, code) in enumerate(reversed(path)):
            tables[c, d] = pnode
            codes[c, d] = code
            valid[c, d] = 1.0

    args = [as_tensor(input), as_tensor(label), as_tensor(weight)]
    if bias is not None:
        args.append(as_tensor(bias))

    def fn(x, y, w, *b):
        t = jnp.asarray(tables)[y]       # [N, depth]
        cde = jnp.asarray(codes)[y]
        msk = jnp.asarray(valid)[y]
        wn = w[t]                        # [N, depth, D]
        logits = jnp.einsum("nd,nkd->nk", x, wn)
        if b:
            logits = logits + b[0][t]
        # sigmoid CE toward the branch code at every internal node;
        # paddle returns the UN-reduced per-sample loss [N, 1]
        ls = jnp.logaddexp(0.0, logits) - cde * logits
        return jnp.sum(ls * msk, axis=1, keepdims=True)
    return apply(fn, *args, name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-style margin softmax (paddle margin_cross_entropy):
    cos(m1*theta + m2) - m3 applied to the target logit."""
    def fn(x, y):
        theta = jnp.arccos(jnp.clip(x, -1.0 + 1e-7, 1.0 - 1e-7))
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(y, x.shape[-1], dtype=x.dtype)
        adj = jnp.where(onehot > 0, tgt, x) * scale
        logp = jax.nn.log_softmax(adj, axis=-1)
        ce = -jnp.take_along_axis(logp, y[:, None], axis=-1)
        if reduction == "mean":
            ce = jnp.mean(ce)
        elif reduction == "sum":
            ce = jnp.sum(ce)
        if return_softmax:
            return ce, jax.nn.softmax(adj, axis=-1)
        return ce
    if return_softmax:
        return apply(fn, as_tensor(logits), as_tensor(label), n_outputs=2,
                     name="margin_cross_entropy")
    return apply(fn, as_tensor(logits), as_tensor(label),
                 name="margin_cross_entropy")


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-T transducer loss: forward-variable DP
    alpha[t][u] = logaddexp(alpha[t-1][u] + blank(t-1,u),
                            alpha[t][u-1] + emit(t,u-1)),
    as a lax.scan over frames with an inner scan over the label axis
    (the U recurrence is inherently sequential).

    Divergence: FastEmit regularization is not implemented (it rescales
    emit-transition gradients, needing the backward DP); the default is
    0.0 here (the reference defaults to 0.001) and a nonzero value warns.
    """
    if fastemit_lambda:
        import warnings
        warnings.warn(
            "rnnt_loss: fastemit_lambda is accepted for API parity but "
            "FastEmit regularization is NOT applied; proceeding with the "
            "plain transducer loss", UserWarning, stacklevel=2)
    acts, labels = as_tensor(input), as_tensor(label)
    ilens, llens = as_tensor(input_lengths), as_tensor(label_lengths)

    def fn(logits, ys, tlen, ulen):
        logp = jax.nn.log_softmax(logits, axis=-1)   # [B, T, U+1, V]
        B, T, U1, _ = logp.shape
        U = U1 - 1
        blank_lp = logp[..., blank]                  # [B, T, U+1]
        idx = jnp.broadcast_to(ys[:, None, :U, None], (B, T, U, 1))
        emit_lp = jnp.take_along_axis(logp[:, :, :U, :], idx,
                                      axis=-1)[..., 0]   # [B, T, U]

        def row_from(horiz, t):
            """alpha[t][:] given horiz[u] = diagonal-move scores."""
            def emit_scan(carry, k):
                cur = jnp.logaddexp(horiz[:, k + 1],
                                    carry + emit_lp[:, t, k])
                return cur, cur
            if U == 0:
                return horiz[:, :1]
            _, em = jax.lax.scan(emit_scan, horiz[:, 0], jnp.arange(U))
            return jnp.concatenate([horiz[:, :1],
                                    jnp.moveaxis(em, 0, 1)], axis=1)

        # t = 0: only emit moves are possible
        neg_inf = jnp.full((B, U), -1e30)
        horiz0 = jnp.concatenate([jnp.zeros((B, 1)), neg_inf], axis=1)
        row0 = row_from(horiz0, 0)

        def step(row, t):
            new = row_from(row + blank_lp[:, t - 1, :], t)
            return new, new

        if T > 1:
            _, rows = jax.lax.scan(step, row0, jnp.arange(1, T))
            rows = jnp.concatenate([row0[None], rows], axis=0)  # [T,B,U+1]
        else:
            rows = row0[None]
        t_idx = jnp.clip(tlen - 1, 0, T - 1)
        u_idx = jnp.clip(ulen, 0, U)
        last_row = jnp.moveaxis(rows, 0, 1)[jnp.arange(B), t_idx]  # [B,U+1]
        final = last_row[jnp.arange(B), u_idx] +             blank_lp[jnp.arange(B), t_idx, u_idx]
        per = -final
        if reduction == "mean":
            return jnp.mean(per)
        if reduction == "sum":
            return jnp.sum(per)
        return per
    return apply(fn, acts, labels, ilens, llens, name="rnnt_loss")


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (Grave et al.): frequent classes in the head,
    rare classes in down-projected tail clusters."""
    n_clusters = len(cutoffs)
    head_size = cutoffs[0] + n_clusters
    args = [as_tensor(input), as_tensor(label), as_tensor(head_weight)]
    tail_flat = []
    for pair in tail_weights:
        tail_flat.extend([as_tensor(pair[0]), as_tensor(pair[1])])
    args.extend(tail_flat)
    if head_bias is not None:
        args.append(as_tensor(head_bias))
    has_bias = head_bias is not None
    cuts = [0] + list(cutoffs)

    def fn(x, y, hw, *rest):
        tails = rest[:2 * n_clusters]
        hb = rest[2 * n_clusters] if has_bias else None
        head = x @ hw
        if hb is not None:
            head = head + hb
        head_lp = jax.nn.log_softmax(head, axis=-1)
        # in-head classes
        in_head = y < cuts[1]
        safe_head = jnp.clip(y, 0, cuts[1] - 1)
        lp = jnp.take_along_axis(head_lp, safe_head[:, None], 1)[:, 0]
        for c in range(n_clusters):
            lo = cuts[c + 1]
            hi = cuts[c + 2] if c + 2 < len(cuts) else None
            in_c = (y >= lo) & ((y < hi) if hi is not None else True)
            proj, cls_w = tails[2 * c], tails[2 * c + 1]
            tail_logits = (x @ proj) @ cls_w
            tail_lp = jax.nn.log_softmax(tail_logits, axis=-1)
            size_c = tail_lp.shape[-1]
            safe_t = jnp.clip(y - lo, 0, size_c - 1)
            cluster_lp = head_lp[:, cuts[1] + c]
            cand = cluster_lp + jnp.take_along_axis(
                tail_lp, safe_t[:, None], 1)[:, 0]
            lp = jnp.where(in_c, cand, lp)
        loss = -jnp.mean(lp)
        return lp, loss
    outs = apply(fn, *args, n_outputs=2,
                 name="adaptive_log_softmax_with_loss")
    return outs[0], outs[1]


__all__ += ["soft_margin_loss", "multi_margin_loss", "npair_loss",
            "triplet_margin_with_distance_loss", "hsigmoid_loss",
            "margin_cross_entropy", "rnnt_loss",
            "adaptive_log_softmax_with_loss"]
