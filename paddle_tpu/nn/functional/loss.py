"""Loss functionals (python/paddle/nn/functional/loss.py parity,
UNVERIFIED)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...ops.common import as_tensor

__all__ = ["cross_entropy", "softmax_with_cross_entropy", "nll_loss",
           "mse_loss", "l1_loss", "smooth_l1_loss", "binary_cross_entropy",
           "binary_cross_entropy_with_logits", "kl_div", "margin_ranking_loss",
           "hinge_embedding_loss", "cosine_embedding_loss", "ctc_loss",
           "triplet_margin_loss", "multi_label_soft_margin_loss",
           "square_error_cost", "log_loss", "sigmoid_focal_loss",
           "poisson_nll_loss", "gaussian_nll_loss", "dice_loss"]


def _reduce(val, reduction):
    if reduction == "mean":
        return jnp.mean(val)
    if reduction == "sum":
        return jnp.sum(val)
    return val


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    input, label = as_tensor(input), as_tensor(label)
    args = [input, label]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(logits, lab, *w):
        lf = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) \
            if use_softmax else jnp.log(jnp.maximum(
                logits.astype(jnp.float32), 1e-38))
        n_classes = logits.shape[axis]
        if soft_label or (lab.ndim == logits.ndim and
                          lab.shape[axis] == n_classes and
                          jnp.issubdtype(lab.dtype, jnp.floating)):
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + \
                    label_smoothing / n_classes
            loss = -jnp.sum(soft * lf, axis=axis)
            mask = None
        else:
            idx = lab
            if idx.ndim == logits.ndim:  # [..., 1] hard labels
                idx = jnp.squeeze(idx, axis=axis)
            idx = idx.astype(jnp.int32)
            mask = (idx != ignore_index)
            safe = jnp.where(mask, idx, 0)
            if label_smoothing > 0:
                oh = jax.nn.one_hot(safe, n_classes, axis=axis,
                                    dtype=jnp.float32)
                soft = oh * (1 - label_smoothing) + \
                    label_smoothing / n_classes
                loss = -jnp.sum(soft * lf, axis=axis)
            else:
                loss = -jnp.take_along_axis(
                    lf, jnp.expand_dims(safe, axis), axis=axis)
                loss = jnp.squeeze(loss, axis=axis)
            if w:
                cw = jnp.take(w[0].astype(jnp.float32), safe)
                loss = loss * cw
            loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            if mask is not None:
                if w:
                    cw = jnp.take(w[0].astype(jnp.float32),
                                  jnp.where(mask, idx, 0)) * mask
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(cw), 1e-12)
                return jnp.sum(loss) / jnp.maximum(
                    jnp.sum(mask.astype(jnp.float32)), 1.0)
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(fn, *args, name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as softmax_fn
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input, label = as_tensor(input), as_tensor(label)
    args = [input, label]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(lp, lab, *w):
        idx = lab.astype(jnp.int32)
        mask = (idx != ignore_index)
        safe = jnp.where(mask, idx, 0)
        loss = -jnp.take_along_axis(lp, safe[:, None] if lp.ndim == 2
                                    else jnp.expand_dims(safe, 1), axis=1)
        loss = jnp.squeeze(loss, axis=1)
        cw = None
        if w:
            cw = jnp.take(w[0], safe)
            loss = loss * cw
        loss = jnp.where(mask, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(cw * mask) if cw is not None else \
                jnp.sum(mask.astype(loss.dtype))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(fn, *args, name="nll_loss")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction),
                 as_tensor(input), as_tensor(label), name="mse_loss")


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), as_tensor(input),
                 as_tensor(label), name="square_error_cost")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 as_tensor(input), as_tensor(label), name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        val = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label),
                 name="smooth_l1_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    args = [as_tensor(input), as_tensor(label)]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        val = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            val = val * w[0]
        return _reduce(val, reduction)
    return apply(fn, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    args = [as_tensor(logit), as_tensor(label)]
    if weight is not None:
        args.append(as_tensor(weight))
    if pos_weight is not None:
        args.append(as_tensor(pos_weight))

    def fn(x, y, *rest):
        i = 0
        w = pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]
        # stable: max(x,0) - x*y + log(1+exp(-|x|)); with pos_weight:
        log_sig_x = jax.nn.log_sigmoid(x)
        log_sig_nx = jax.nn.log_sigmoid(-x)
        if pw is not None:
            val = -(pw * y * log_sig_x + (1 - y) * log_sig_nx)
        else:
            val = -(y * log_sig_x + (1 - y) * log_sig_nx)
        if w is not None:
            val = val * w
        return _reduce(val, reduction)
    return apply(fn, *args, name="bce_with_logits")


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, y):
        if log_target:
            val = jnp.exp(y) * (y - lp)
        else:
            val = y * (jnp.log(jnp.maximum(y, 1e-38)) - lp)
        if reduction == "batchmean":
            return jnp.sum(val) / lp.shape[0]
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label), name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def fn(a, b, y):
        val = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(other), as_tensor(label),
                 name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def fn(a, y):
        val = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label),
                 name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        val = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input1), as_tensor(input2), as_tensor(label),
                 name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-06, swap=False, reduction="mean",
                        name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, -1) ** (1 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        val = jnp.maximum(0.0, d_ap - d_an + margin)
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(positive),
                 as_tensor(negative), name="triplet_margin_loss")


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    args = [as_tensor(input), as_tensor(label)]
    if weight is not None:
        args.append(as_tensor(weight))

    def fn(x, y, *w):
        val = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        val = jnp.mean(val, -1)
        if w:
            val = val * w[0]
        return _reduce(val, reduction)
    return apply(fn, *args, name="multi_label_soft_margin_loss")


def log_loss(input, label, epsilon=0.0001, name=None):
    def fn(p, y):
        return -(y * jnp.log(p + epsilon) +
                 (1 - y) * jnp.log(1 - p + epsilon))
    return apply(fn, as_tensor(input), as_tensor(label), name="log_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    args = [as_tensor(logit), as_tensor(label)]
    if normalizer is not None:
        args.append(as_tensor(normalizer))

    def fn(x, y, *nm):
        p = jax.nn.sigmoid(x)
        ce = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        val = a_t * ((1 - p_t) ** gamma) * ce
        if nm:
            val = val / nm[0]
        return _reduce(val, reduction)
    return apply(fn, *args, name="sigmoid_focal_loss")


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(x, y):
        if log_input:
            val = jnp.exp(x) - y * x
        else:
            val = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + (y == 0)) - y + \
                0.5 * jnp.log(2 * jnp.pi * jnp.maximum(y, 1.0))
            val = val + jnp.where(y > 1, stirling, 0.0)
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label),
                 name="poisson_nll_loss")


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        val = 0.5 * (jnp.log(var) + jnp.square(y - mu) / var)
        if full:
            val = val + 0.5 * jnp.log(jnp.asarray(2 * jnp.pi, var.dtype))
        return _reduce(val, reduction)
    return apply(fn, as_tensor(input), as_tensor(label), as_tensor(variance),
                 name="gaussian_nll_loss")


def dice_loss(input, label, epsilon=1e-05, name=None):
    def fn(p, y):
        yf = jax.nn.one_hot(jnp.squeeze(y, -1), p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * yf, axis=tuple(range(1, p.ndim)))
        union = jnp.sum(p, axis=tuple(range(1, p.ndim))) + \
            jnp.sum(yf, axis=tuple(range(1, p.ndim)))
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply(fn, as_tensor(input), as_tensor(label), name="dice_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    # log_probs: [T, N, C] (paddle layout)
    lp = as_tensor(log_probs)
    lab = as_tensor(labels)
    il = as_tensor(input_lengths)
    ll = as_tensor(label_lengths)

    def fn(logp, ys, in_len, lab_len):
        logp = jnp.transpose(logp, (1, 0, 2))  # [N, T, C]
        logp = jax.nn.log_softmax(logp, -1)
        N, T, C = logp.shape
        S = ys.shape[1]
        # classic alpha recursion over extended label seq with blanks
        ext = jnp.full((N, 2 * S + 1), blank, dtype=ys.dtype)
        ext = ext.at[:, 1::2].set(ys)
        L = 2 * lab_len + 1

        def get(logp_t, idx):
            return jnp.take_along_axis(logp_t, idx, axis=-1)

        neg_inf = -1e30
        alpha0 = jnp.full((N, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
        first_lab = get(logp[:, 0], ext[:, 1:2])[:, 0]
        alpha0 = alpha0.at[:, 1].set(jnp.where(S > 0, first_lab, neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), dtype=bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, logp_t):
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            m = jnp.maximum(jnp.maximum(a_prev, a_shift1), a_shift2)
            summed = m + jnp.log(
                jnp.exp(a_prev - m) + jnp.exp(a_shift1 - m) +
                jnp.exp(a_shift2 - m) + 1e-38)
            emit = get(logp_t, ext)
            return summed + emit, None

        def scan_fn(alpha, t):
            new_alpha, _ = step(alpha, logp[:, t])
            keep = (t < in_len)[:, None]
            return jnp.where(keep, new_alpha, alpha), None

        alpha, _ = jax.lax.scan(scan_fn, alpha0, jnp.arange(1, T))
        idx_last = (L - 1)[:, None]
        idx_prev = jnp.maximum(L - 2, 0)[:, None]
        a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
        a_prev = jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0]
        m = jnp.maximum(a_last, a_prev)
        ll_prob = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
        loss = -ll_prob
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len, 1))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss
    return apply(fn, lp, lab, il, ll, name="ctc_loss")
