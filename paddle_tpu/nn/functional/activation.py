"""Activation functionals (python/paddle/nn/functional/activation.py parity,
UNVERIFIED). All are pure jnp/jax.nn compositions; XLA fuses them into
adjacent matmuls on TPU."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply, tape_alias, tape_rebind
from ...ops.common import as_tensor

__all__ = ["relu", "relu_", "relu6", "gelu", "silu", "swish", "sigmoid",
           "tanh", "softmax", "softmax_", "log_softmax", "leaky_relu", "elu",
           "elu_", "selu", "celu", "hardswish", "hardsigmoid", "hardtanh",
           "hardshrink", "softshrink", "tanhshrink", "mish", "prelu", "glu",
           "swiglu", "maxout", "softplus", "softsign", "thresholded_relu",
           "log_sigmoid", "gumbel_softmax", "rrelu"]


def relu(x, name=None):
    return apply(jax.nn.relu, as_tensor(x), name="relu")


def relu_(x, name=None):
    return tape_rebind(x, relu(tape_alias(x)))


def relu6(x, name=None):
    return apply(jax.nn.relu6, as_tensor(x), name="relu6")


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate),
                 as_tensor(x), name="gelu")


def silu(x, name=None):
    return apply(jax.nn.silu, as_tensor(x), name="silu")


def swish(x, name=None):
    return silu(x)


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, as_tensor(x), name="sigmoid")


def tanh(x, name=None):
    return apply(jnp.tanh, as_tensor(x), name="tanh")


def softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    from ...framework.core import to_jax_dtype
    jd = to_jax_dtype(dtype)

    def fn(a):
        if jd is not None:
            a = a.astype(jd)
        return jax.nn.softmax(a, axis=int(axis))
    return apply(fn, x, name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    return tape_rebind(x, softmax(tape_alias(x), axis, dtype))


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = as_tensor(x)
    from ...framework.core import to_jax_dtype
    jd = to_jax_dtype(dtype)

    def fn(a):
        if jd is not None:
            a = a.astype(jd)
        return jax.nn.log_softmax(a, axis=int(axis))
    return apply(fn, x, name="log_softmax")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope),
                 as_tensor(x), name="leaky_relu")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), as_tensor(x), name="elu")


def elu_(x, alpha=1.0, name=None):
    return tape_rebind(x, elu(tape_alias(x), alpha))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a,
                                             alpha * jnp.expm1(a)),
                 as_tensor(x), name="selu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), as_tensor(x), name="celu")


def hardswish(x, name=None):
    return apply(jax.nn.hard_swish, as_tensor(x), name="hardswish")


def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0),
                 as_tensor(x), name="hardsigmoid")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), as_tensor(x),
                 name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0),
                 as_tensor(x), name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(a > threshold, a - threshold,
                                     jnp.where(a < -threshold, a + threshold,
                                               0.0)),
                 as_tensor(x), name="softshrink")


def tanhshrink(x, name=None):
    return apply(lambda a: a - jnp.tanh(a), as_tensor(x), name="tanhshrink")


def mish(x, name=None):
    return apply(lambda a: a * jnp.tanh(jax.nn.softplus(a)), as_tensor(x),
                 name="mish")


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = as_tensor(x), as_tensor(weight)

    def fn(a, w):
        if w.size > 1:
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape = [1] * a.ndim
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)
    return apply(fn, x, weight, name="prelu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    x = as_tensor(x)
    if training:
        from ...framework import random as fr
        import jax.random as jr
        key = fr.default_generator.next_key()
        slope = jr.uniform(key, tuple(x.shape), jnp.float32, lower, upper)
        return apply(lambda a: jnp.where(a >= 0, a, slope.astype(a.dtype) * a),
                     x, name="rrelu")
    mid = (lower + upper) / 2.0
    return apply(lambda a: jnp.where(a >= 0, a, mid * a), x, name="rrelu")


def glu(x, axis=-1, name=None):
    def fn(a):
        u, v = jnp.split(a, 2, axis=axis)
        return u * jax.nn.sigmoid(v)
    return apply(fn, as_tensor(x), name="glu")


def _use_fused_swiglu() -> bool:
    from ...framework import flags
    if not (flags.flag("FLAGS_fused_swiglu")
            and flags.flag("FLAGS_enable_pallas_kernels")):
        return False
    return jax.default_backend() == "tpu"


def swiglu(x, y=None, name=None):
    if y is not None:
        if _use_fused_swiglu():
            # one VMEM pass + fused dgate/dup backward, no silu
            # intermediate saved (ops/pallas/swiglu.py)
            from ...ops.pallas import swiglu as pallas_sw
            return apply(pallas_sw.swiglu_fused, as_tensor(x),
                         as_tensor(y), name="fused_swiglu")
        return apply(lambda a, b: jax.nn.silu(a) * b, as_tensor(x),
                     as_tensor(y), name="swiglu")

    def fn(a):
        u, v = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(u) * v
    return apply(fn, as_tensor(x), name="swiglu")


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply(fn, as_tensor(x), name="maxout")


def softplus(x, beta=1, threshold=20, name=None):
    from ...ops.math import softplus as _sp
    return _sp(x, beta, threshold)


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, as_tensor(x), name="softsign")


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply(lambda a: jnp.where(a > threshold, a, value), as_tensor(x),
                 name="thresholded_relu")


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, as_tensor(x), name="log_sigmoid")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    x = as_tensor(x)
    from ...framework import random as fr
    import jax.random as jr
    key = fr.default_generator.next_key()
    g = jr.gumbel(key, tuple(x.shape), jnp.float32)

    def fn(a):
        y = jax.nn.softmax((a + g.astype(a.dtype)) / temperature, axis=axis)
        if hard:
            # straight-through: hard one-hot forward, soft gradient
            oh = jax.nn.one_hot(jnp.argmax(y, axis=axis), y.shape[axis],
                                axis=axis, dtype=y.dtype)
            return oh + y - jax.lax.stop_gradient(y)
        return y
    return apply(fn, x, name="gumbel_softmax")
