"""Normalization functionals (python/paddle/nn/functional/norm.py parity,
UNVERIFIED). ``rms_norm``/``layer_norm`` route to Pallas kernels on TPU when
enabled (SURVEY.md §2.1 PHI fused kernels → Pallas)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...framework import flags
from ...ops.common import as_tensor

__all__ = ["layer_norm", "batch_norm", "instance_norm", "group_norm",
           "spectral_norm",
           "local_response_norm", "rms_norm", "fused_rms_norm_residual"]


def _use_pallas() -> bool:
    if not flags.flag("FLAGS_enable_pallas_kernels"):
        return False
    return jax.default_backend() == "tpu"


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = as_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))

    args = [x]
    if weight is not None:
        args.append(as_tensor(weight))
    if bias is not None:
        args.append(as_tensor(bias))

    def fn(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = (a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        out = out.astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out
    return apply(fn, *args, name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm — fused Pallas kernel on TPU, jnp fallback elsewhere."""
    x = as_tensor(x)
    if weight is not None:
        w = as_tensor(weight)
        if _use_pallas():
            from ...ops.pallas import rms_norm as pallas_rms
            return apply(lambda a, ww: pallas_rms.rms_norm(a, ww, epsilon),
                         x, w, name="rms_norm")

        def fn(a, ww):
            dt = a.dtype
            af = a.astype(jnp.float32)
            ms = jnp.mean(jnp.square(af), axis=-1, keepdims=True)
            return (af * jax.lax.rsqrt(ms + epsilon)).astype(dt) * ww
        return apply(fn, x, w, name="rms_norm")

    def fn(a):
        dt = a.dtype
        af = a.astype(jnp.float32)
        ms = jnp.mean(jnp.square(af), axis=-1, keepdims=True)
        return (af * jax.lax.rsqrt(ms + epsilon)).astype(dt)
    return apply(fn, x, name="rms_norm")


def fused_rms_norm_residual(x, residual, weight, epsilon=1e-6, name=None):
    """``(rms_norm(x + residual) * weight, x + residual)`` — the
    decoder-layer residual-add + norm pair as ONE op: the fused Pallas
    kernel on TPU (ops/pallas/rms_norm.rms_norm_residual, one VMEM
    pass for both outputs, fused dx/dresidual backward), and the
    identical-math jnp pairing elsewhere (the add happens in the input
    dtype, then the f32 norm — bit-parity with the unfused
    ``x + residual`` followed by :func:`rms_norm`)."""
    x, r, w = as_tensor(x), as_tensor(residual), as_tensor(weight)
    from ...ops.pallas import rms_norm as pallas_rms
    if _use_pallas():
        return apply(
            lambda a, b, ww: pallas_rms.rms_norm_residual(a, b, ww,
                                                          epsilon),
            x, r, w, n_outputs=2, name="fused_rms_norm_residual")
    # the SAME oracle the interpret-mode parity tests pin the kernel to
    # — one source of truth for the fallback math
    return apply(
        lambda a, b, ww: pallas_rms.rms_norm_residual_reference(
            a, b, ww, epsilon),
        x, r, w, n_outputs=2, name="fused_rms_norm_residual")


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    x = as_tensor(x)
    ch_axis = x.ndim - 1 if data_format[-1] == "C" and x.ndim > 2 else 1
    if x.ndim == 2:
        ch_axis = 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # update running stats eagerly (buffer mutation, like paddle)
        xf = x._data.astype(jnp.float32)
        batch_mean = jnp.mean(xf, axis=reduce_axes)
        batch_var = jnp.var(xf, axis=reduce_axes)
        if running_mean is not None:
            running_mean.set_data(
                (momentum * running_mean._data.astype(jnp.float32)
                 + (1 - momentum) * batch_mean).astype(running_mean.dtype))
            running_var.set_data(
                (momentum * running_var._data.astype(jnp.float32)
                 + (1 - momentum) * batch_var).astype(running_var.dtype))

        def fn(a, *wb):
            af = a.astype(jnp.float32)
            m = jnp.mean(af, axis=reduce_axes, keepdims=True)
            v = jnp.var(af, axis=reduce_axes, keepdims=True)
            out = (af - m) * jax.lax.rsqrt(v + epsilon)
            out = out.astype(a.dtype)
            return _affine(out, wb, ch_axis, weight, bias)
        args = [x] + _wb_args(weight, bias)
        return apply(fn, *args, name="batch_norm")

    rm, rv = as_tensor(running_mean), as_tensor(running_var)

    def fn(a, m, v, *wb):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a.astype(jnp.float32) - m.astype(jnp.float32).reshape(shape)) \
            * jax.lax.rsqrt(v.astype(jnp.float32).reshape(shape) + epsilon)
        out = out.astype(a.dtype)
        return _affine(out, wb, ch_axis, weight, bias)
    args = [x, rm, rv] + _wb_args(weight, bias)
    return apply(fn, *args, name="batch_norm")


def _wb_args(weight, bias):
    out = []
    if weight is not None:
        out.append(as_tensor(weight))
    if bias is not None:
        out.append(as_tensor(bias))
    return out


def _affine(out, wb, ch_axis, weight, bias):
    shape = [1] * out.ndim
    shape[ch_axis] = out.shape[ch_axis]
    i = 0
    if weight is not None:
        out = out * wb[i].reshape(shape)
        i += 1
    if bias is not None:
        out = out + wb[i].reshape(shape)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9,
                  epsilon=1e-05, data_format="NCHW", name=None):
    x = as_tensor(x)
    ch_axis = 1
    reduce_axes = tuple(range(2, x.ndim))

    def fn(a, *wb):
        af = a.astype(jnp.float32)
        m = jnp.mean(af, axis=reduce_axes, keepdims=True)
        v = jnp.var(af, axis=reduce_axes, keepdims=True)
        out = ((af - m) * jax.lax.rsqrt(v + epsilon)).astype(a.dtype)
        return _affine(out, wb, ch_axis, weight, bias)
    args = [x] + _wb_args(weight, bias)
    return apply(fn, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = as_tensor(x)
    channel_last = data_format[-1] == "C" and x.ndim > 2

    def fn(a, *wb):
        if channel_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        g = num_groups
        grouped = a_t.reshape((n, g, c // g) + a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        gf = grouped.astype(jnp.float32)
        m = jnp.mean(gf, axis=axes, keepdims=True)
        v = jnp.var(gf, axis=axes, keepdims=True)
        out = ((gf - m) * jax.lax.rsqrt(v + epsilon)).astype(a.dtype)
        out = out.reshape(a_t.shape)
        out = _affine(out, wb, 1, weight, bias)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = [x] + _wb_args(weight, bias)
    return apply(fn, *args, name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = as_tensor(x)

    def fn(a):
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[ch_axis]
        sq_m = jnp.moveaxis(sq, ch_axis, 0)
        pad_width = [(half, size - 1 - half)] + [(0, 0)] * (a.ndim - 1)
        padded = jnp.pad(sq_m, pad_width)
        acc = jnp.zeros_like(sq_m)
        for i in range(size):
            acc = acc + padded[i:i + c]
        denom = (k + alpha * acc) ** beta
        return a / jnp.moveaxis(denom, 0, ch_axis)
    return apply(fn, x, name="local_response_norm")


def spectral_norm(x, weight_u, weight_v, dim=0, power_iters=1,
                  eps=1e-12, name=None):
    """Functional spectral norm (reference
    ``paddle.nn.functional.spectral_norm``): normalize weight ``x`` by
    its largest singular value, estimated by ``power_iters`` rounds of
    power iteration from the CALLER-OWNED u/v vectors (the
    ``nn.SpectralNorm`` layer holds them as buffers and delegates
    here)."""
    from ...framework.core import Tensor, apply

    u0 = weight_u.jax() if isinstance(weight_u, Tensor) else \
        jnp.asarray(weight_u)
    v0 = weight_v.jax() if isinstance(weight_v, Tensor) else \
        jnp.asarray(weight_v)

    def fn(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u, v = u0, v0
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma

    return apply(fn, x, name="spectral_norm")
