"""Common functionals: linear, dropout, embedding, normalize, pad,
interpolate, unfold … (python/paddle/nn/functional/common.py parity,
UNVERIFIED)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply, to_jax_dtype
from ...framework import random as framework_random
from ...ops.common import as_tensor

__all__ = ["linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
           "feature_alpha_dropout",
           "embedding", "normalize", "cosine_similarity", "pad",
           "interpolate", "upsample", "unfold", "fold", "pixel_shuffle",
           "pixel_unshuffle", "channel_shuffle", "label_smooth",
           "pairwise_distance", "bilinear", "pdist"]


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Paddle stores Linear weight as [in, out]."""
    from ...amp.auto_cast import maybe_cast_matmul
    x, weight = maybe_cast_matmul(as_tensor(x), as_tensor(weight))
    if bias is not None:
        def fn(a, w, b):
            y = a @ w
            return y + b.astype(y.dtype)
        return apply(fn, x, weight, as_tensor(bias), name="linear")
    return apply(lambda a, w: a @ w, x, weight, name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    x = as_tensor(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1.0 - p), x, name="dropout")
        return x
    key = framework_random.default_generator.next_key()
    shape = tuple(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)

    def fn(a):
        m = keep.astype(a.dtype)
        if mode == "upscale_in_train":
            return a * m / (1.0 - p)
        return a * m
    return apply(fn, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ch_axis = 1 if data_format == "NCHW" else 3
    return dropout(x, p, axis=[0, ch_axis], training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ch_axis = 1 if data_format == "NCDHW" else 4
    return dropout(x, p, axis=[0, ch_axis], training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = framework_random.default_generator.next_key()
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(x.shape))
    a_coef = (1.0 - p + p * alpha_p ** 2 * (1.0 - p)) ** -0.5
    b_coef = -a_coef * p * alpha_p

    def fn(a):
        m = keep.astype(a.dtype)
        return a_coef * (a * m + alpha_p * (1 - m)) + b_coef
    return apply(fn, x, name="alpha_dropout")


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout that drops whole channels (dim 1), keeping SELU
    self-normalizing statistics (paddle.nn.functional parity)."""
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = framework_random.default_generator.next_key()
    mask_shape = tuple(s if d <= 1 else 1 for d, s in enumerate(x.shape))
    keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
    a_coef = (1.0 - p + p * alpha_p ** 2 * (1.0 - p)) ** -0.5
    b_coef = -a_coef * p * alpha_p

    def fn(a):
        m = jnp.broadcast_to(keep, a.shape).astype(a.dtype)
        return a_coef * (a * m + alpha_p * (1 - m)) + b_coef
    return apply(fn, x, name="feature_alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = as_tensor(x), as_tensor(weight)

    def fn(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids != padding_idx)[..., None].astype(out.dtype)
            out = out * mask
        return out
    return apply(fn, x, weight, name="embedding")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = as_tensor(x)

    def fn(a):
        if p == 2:
            n = jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=True))
        else:
            n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return apply(fn, x, name="normalize")


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply(fn, as_tensor(x1), as_tensor(x2), name="cosine_similarity")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    return apply(fn, as_tensor(x), as_tensor(y), name="pairwise_distance")


def pdist(x, p=2.0, name=None):
    def fn(a):
        d = a[:, None, :] - a[None, :, :]
        dist = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
        iu = jnp.triu_indices(a.shape[0], k=1)
        return dist[iu]
    return apply(fn, as_tensor(x), name="pdist")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...ops.manipulation import pad as _pad
    return _pad(x, pad, mode, value, data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = as_tensor(x1), as_tensor(x2), as_tensor(weight)

    def fn(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            out = out + bb[0]
        return out
    if bias is not None:
        return apply(fn, x1, x2, weight, as_tensor(bias), name="bilinear")
    return apply(fn, x1, x2, weight, name="bilinear")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = as_tensor(x)
    nd = x.ndim
    spatial = nd - 2
    channel_last = data_format.endswith("C") or data_format in ("NHWC", "NWC",
                                                                "NDHWC")
    if channel_last:
        sp_shape = x.shape[1:-1]
    else:
        sp_shape = x.shape[2:]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * spatial
        size = [int(s * f) for s, f in zip(sp_shape, scale_factor)]
    else:
        if isinstance(size, Tensor):
            size = [int(v) for v in size.tolist()]
        size = [int(s.item()) if isinstance(s, Tensor) else int(s)
                for s in size]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def fn(a):
        if channel_last:
            out_shape = (a.shape[0],) + tuple(size) + (a.shape[-1],)
        else:
            out_shape = a.shape[:2] + tuple(size)
        if mode == "nearest":
            return jax.image.resize(a, out_shape, method="nearest")
        if align_corners:
            # jax.image.resize has no align_corners; emulate via manual
            # coordinate map using scale_and_translate
            in_sp = sp_shape
            scales = [(o - 1) / (i - 1) if i > 1 else 1.0
                      for i, o in zip(in_sp, size)]
            sp_dims = list(range(1, nd - 1)) if channel_last else \
                list(range(2, nd))
            return jax.image.scale_and_translate(
                a, out_shape, sp_dims,
                jnp.asarray(scales, jnp.float32),
                jnp.zeros((spatial,), jnp.float32),
                method={"linear": "linear", "cubic": "cubic"}[jmode],
                antialias=False)
        return jax.image.resize(a, out_shape, method=jmode, antialias=False)
    return apply(fn, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = as_tensor(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    d = _pair(dilations)
    if isinstance(paddings, int):
        p = ((paddings, paddings), (paddings, paddings))
    elif len(paddings) == 2:
        p = ((paddings[0], paddings[0]), (paddings[1], paddings[1]))
    else:
        p = ((paddings[0], paddings[2]), (paddings[1], paddings[3]))

    def fn(a):
        n, c, h, w = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s, padding=p,
            rhs_dilation=d, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [N, C*kh*kw, oh, ow]
        return patches.reshape(n, patches.shape[1], -1)
    return apply(fn, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    x = as_tensor(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    out_sz = _pair(output_sizes)
    k = _pair(kernel_sizes)
    s = _pair(strides)
    d = _pair(dilations)
    pd = _pair(paddings) if not isinstance(paddings, int) else (paddings,
                                                                paddings)

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        oh = (out_sz[0] + 2 * pd[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (out_sz[1] + 2 * pd[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = a.reshape(n, c, k[0], k[1], oh, ow)
        out = jnp.zeros((n, c, out_sz[0] + 2 * pd[0], out_sz[1] + 2 * pd[1]),
                        a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                hi = i * d[0]
                wj = j * d[1]
                out = out.at[:, :, hi:hi + oh * s[0]:s[0],
                             wj:wj + ow * s[1]:s[1]].add(cols[:, :, i, j])
        return out[:, :, pd[0]:pd[0] + out_sz[0], pd[1]:pd[1] + out_sz[1]]
    return apply(fn, x, name="fold")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))
    return apply(fn, as_tensor(x), name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)
    return apply(fn, as_tensor(x), name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        return a.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return apply(fn, as_tensor(x), name="channel_shuffle")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = as_tensor(label)

    def fn(l):
        k = l.shape[-1]
        if prior_dist is not None:
            pd = prior_dist._data if isinstance(prior_dist, Tensor) \
                else jnp.asarray(prior_dist)
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k
    return apply(fn, label, name="label_smooth")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a 2D sampling grid from batched affine matrices
    (paddle.nn.functional.affine_grid). theta: [N, 2, 3];
    out_shape: [N, C, H, W]; returns [N, H, W, 2] (x, y) in [-1, 1]."""
    n, _, h, w = (int(s) for s in out_shape)
    theta = as_tensor(theta)
    if int(theta.shape[0]) != n:
        raise ValueError(
            f"affine_grid: theta batch {theta.shape[0]} does not match "
            f"out_shape batch {n}")

    def fn(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, w)
            ys = jnp.linspace(-1.0, 1.0, h)
        else:
            xs = (jnp.arange(w) * 2 + 1) / w - 1.0
            ys = (jnp.arange(h) * 2 + 1) / h - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # [H, W, 3]
        # highest precision: TPU default matmul precision truncates the
        # coordinates to bf16 (~0.5-pixel offsets at 512px)
        return jnp.einsum("hwk,njk->nhwj", base.astype(th.dtype), th,
                          precision=jax.lax.Precision.HIGHEST)

    return apply(fn, theta, name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N, C, H, W] at grid [N, Ho, Wo, 2] of (x, y) coords in
    [-1, 1] (paddle.nn.functional.grid_sample) — vectorized gather +
    weighted sum; no scatter."""

    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be 'bilinear' or "
                         f"'nearest', got {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"grid_sample padding_mode must be 'zeros', "
                         f"'border' or 'reflection', got {padding_mode!r}")

    def fn(xa, ga):
        N, C, H, W = xa.shape

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1.0) * (size - 1) / 2.0
            return ((coord + 1.0) * size - 1.0) / 2.0

        gx = unnorm(ga[..., 0].astype(jnp.float32), W)  # [N, Ho, Wo]
        gy = unnorm(ga[..., 1].astype(jnp.float32), H)

        def reflect(coord, size):
            # reflect into [0, size-1] (align_corners) / [-0.5, size-0.5]
            if align_corners:
                span = 2.0 * (size - 1)
                if size == 1:
                    return jnp.zeros_like(coord)
                c = jnp.mod(jnp.abs(coord), span)
                return jnp.where(c > size - 1, span - c, c)
            span = 2.0 * size
            c = jnp.mod(jnp.abs(coord + 0.5), span)
            c = jnp.where(c > size, span - c, c) - 0.5
            return jnp.clip(c, 0, size - 1)

        if padding_mode == "border":
            gx = jnp.clip(gx, 0, W - 1)
            gy = jnp.clip(gy, 0, H - 1)
        elif padding_mode == "reflection":
            gx = reflect(gx, W)
            gy = reflect(gy, H)

        def gather(img, yi, xi, valid):
            # img [C, H, W]; yi/xi int [Ho, Wo]
            out = img[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
            return out * valid

        def one(img, sy, sx):
            if mode == "nearest":
                yi = jnp.round(sy).astype(jnp.int32)
                xi = jnp.round(sx).astype(jnp.int32)
                valid = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)) \
                    if padding_mode == "zeros" else jnp.ones_like(yi,
                                                                  jnp.bool_)
                return gather(img, yi, xi, valid)
            y0 = jnp.floor(sy)
            x0 = jnp.floor(sx)
            wy1, wx1 = sy - y0, sx - x0
            wy0, wx0 = 1.0 - wy1, 1.0 - wx1
            total = 0.0
            for dy, wy in ((0, wy0), (1, wy1)):
                for dx, wx in ((0, wx0), (1, wx1)):
                    yi = (y0 + dy).astype(jnp.int32)
                    xi = (x0 + dx).astype(jnp.int32)
                    valid = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)) \
                        if padding_mode == "zeros" else \
                        jnp.ones_like(yi, jnp.bool_)
                    total = total + gather(img, yi, xi, valid) * (wy * wx)
            return total

        out = jax.vmap(one)(xa.astype(jnp.float32), gy, gx)
        return out.astype(xa.dtype)

    return apply(fn, as_tensor(x), as_tensor(grid), name="grid_sample")


__all__ += ["affine_grid", "grid_sample"]


# ---- round-2 breadth -------------------------------------------------------

def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """[...,] lengths -> [..., maxlen] 0/1 mask (paddle sequence_mask)."""
    x = as_tensor(x)
    if maxlen is None:
        import numpy as _np
        maxlen = int(_np.asarray(x._data).max())
    m = int(maxlen)

    def fn(a):
        rng = jnp.arange(m)
        return (rng < a[..., None]).astype(to_jax_dtype(dtype))
    return apply(fn, x, name="sequence_mask", differentiable=False)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal shift: part of the channels shift one step forward /
    backward along the segment (time) axis."""
    x = as_tensor(x)

    def fn(a):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        nt, c, h, w = a.shape
        n = nt // int(seg_num)
        v = a.reshape(n, int(seg_num), c, h, w)
        fold = int(c * shift_ratio)
        back = jnp.concatenate(
            [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold:2 * fold]),
             v[:, :-1, fold:2 * fold]], axis=1)
        keep = v[:, :, 2 * fold:]
        out = jnp.concatenate([back, fwd, keep], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply(fn, x, name="temporal_shift")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    x = as_tensor(x)
    l, r, t, b = (padding if isinstance(padding, (list, tuple))
                  else [int(padding)] * 4)

    def fn(a):
        if data_format == "NHWC":
            cfg = ((0, 0), (t, b), (l, r), (0, 0))
        else:
            cfg = ((0, 0), (0, 0), (t, b), (l, r))
        return jnp.pad(a, cfg)
    return apply(fn, x, name="zeropad2d")


def gather_tree(ids, parents, name=None):
    """Reconstruct full beam-search sequences from per-step ids and parent
    beam indices ([T, B, W] layout, paddle.nn.functional.gather_tree)."""
    ids_t, par_t = as_tensor(ids), as_tensor(parents)

    def fn(idd, par):
        T = idd.shape[0]

        def step(beam, t):
            # beam: [B, W] current beam index at step t+1; emit ids[t]
            picked = jnp.take_along_axis(idd[t], beam, axis=-1)
            parent = jnp.take_along_axis(par[t], beam, axis=-1)
            return parent, picked

        init = jnp.broadcast_to(jnp.arange(idd.shape[-1]),
                                idd.shape[1:]).astype(idd.dtype)
        _, out = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return out[::-1]
    return apply(fn, ids_t, par_t, name="gather_tree",
                 differentiable=False)


def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """Sample negative class centers (PartialFC): returns the remapped
    labels and the sorted unique set of sampled class ids. Single-process
    TPU variant of the reference's distributed sampler."""
    import numpy as _np
    lab = as_tensor(label)
    host = _np.asarray(lab._data)
    pos = _np.unique(host)
    n_extra = max(int(num_samples) - pos.size, 0)
    rest = _np.setdiff1d(_np.arange(int(num_classes)), pos)
    # fresh negatives every call, reproducible under paddle.seed (the
    # framework RNG hands out a distinct subkey per draw)
    seed = int(jax.random.randint(framework_random.next_key(),
                                  (), 0, 2 ** 31 - 1))
    rng = _np.random.default_rng(seed)
    extra = rng.choice(rest, size=min(n_extra, rest.size), replace=False) \
        if n_extra and rest.size else _np.empty((0,), host.dtype)
    sampled = _np.sort(_np.concatenate([pos, extra.astype(host.dtype)]))
    remap = {c: i for i, c in enumerate(sampled.tolist())}
    remapped = _np.asarray([remap[c] for c in host.tolist()], host.dtype)
    from ...framework.core import Tensor as _T
    return _T(jnp.asarray(remapped)), _T(jnp.asarray(sampled))


__all__ += ["sequence_mask", "temporal_shift", "zeropad2d", "gather_tree",
            "class_center_sample"]
