"""Pooling functionals via ``jax.lax.reduce_window``
(python/paddle/nn/functional/pooling.py parity, UNVERIFIED)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply
from ...ops.common import as_tensor

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d",
           "max_pool2d", "max_pool3d", "adaptive_avg_pool1d",
           "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d"]


def _tuplize(v, n):
    if v is None:
        return None
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


def _pool(x, kernel, stride, padding, n, op, channel_last, ceil_mode=False,
          exclusive=True, count_include_pad=False, name=""):
    x = as_tensor(x)
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride, n) or kernel
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        p = _tuplize(padding, n) if not (isinstance(padding, (list, tuple))
                                         and len(padding) == 2 * n) else None
        if p is not None:
            pads = [(pi, pi) for pi in p]
        else:
            pads = [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]

    def fn(a):
        nd = a.ndim
        if channel_last:
            sp_dims = list(range(1, nd - 1))
        else:
            sp_dims = list(range(2, nd))
        window = [1] * nd
        strides = [1] * nd
        padding_full = [(0, 0)] * nd
        for i, d in enumerate(sp_dims):
            window[d] = kernel[i]
            strides[d] = stride[i]
            if pads is not None:
                padding_full[d] = pads[i]
        if ceil_mode and pad_mode is None:
            # ceil output sizing = extend the high-side padding so the
            # partial tail window is produced. reduce_window pads with
            # the init value (-inf for max, 0 for avg), so tail windows
            # stay correct; the exclusive-avg count uses the same
            # padding on a ones array and also stays correct.
            for i, d in enumerate(sp_dims):
                lo, hi = padding_full[d]
                s_in = a.shape[d]
                out_ceil = -(-(s_in + lo + hi - window[d]) //
                             strides[d]) + 1
                # the last window must START inside input+left-pad
                # (paddle/torch rule) — otherwise it would be all padding
                if (out_ceil - 1) * strides[d] >= s_in + lo:
                    out_ceil -= 1
                need = (out_ceil - 1) * strides[d] + window[d] \
                    - (s_in + lo + hi)
                if need > 0:
                    padding_full[d] = (lo, hi + need)
        if pad_mode == "SAME":
            padding_cfg = "SAME"
        elif pad_mode == "VALID":
            padding_cfg = "VALID"
        else:
            padding_cfg = padding_full
        if op == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) \
                else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window,
                                         strides, padding_cfg)
        # avg
        s = jax.lax.reduce_window(a, 0.0 if jnp.issubdtype(
            a.dtype, jnp.floating) else 0, jax.lax.add, window, strides,
            padding_cfg)
        if exclusive and not count_include_pad and padding_cfg not in \
                ("VALID",):
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                        strides, padding_cfg)
            return s / cnt
        return s / float(np.prod(kernel))
    return apply(fn, x, name=name)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", False,
                 ceil_mode, exclusive, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg",
                 data_format == "NHWC", ceil_mode, exclusive,
                 name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg",
                 data_format == "NDHWC", ceil_mode, exclusive,
                 name="avg_pool3d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "max", False,
                ceil_mode, name="max_pool1d")
    if return_mask:
        idx = _max_pool_indices_nd(as_tensor(x), kernel_size, stride,
                                   padding, 1, False, ceil_mode)
        return out, idx
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, "max",
                data_format == "NHWC", ceil_mode, name="max_pool2d")
    if return_mask:
        idx = _max_pool_indices_nd(as_tensor(x), kernel_size, stride,
                                   padding, 2, data_format == "NHWC",
                                   ceil_mode)
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, "max",
                data_format == "NDHWC", ceil_mode, name="max_pool3d")
    if return_mask:
        idx = _max_pool_indices_nd(as_tensor(x), kernel_size, stride,
                                   padding, 3, data_format == "NDHWC",
                                   ceil_mode)
        return out, idx
    return out


def _adaptive_pool(x, output_size, n, op, channel_last, name):
    x = as_tensor(x)
    out_sz = _tuplize(output_size, n)
    out_sz = tuple(o if o is not None else -1 for o in out_sz)

    def fn(a):
        nd = a.ndim
        sp_dims = list(range(1, nd - 1)) if channel_last else \
            list(range(2, nd))
        out = a
        for i, d in enumerate(sp_dims):
            o = out.shape[d] if out_sz[i] == -1 else out_sz[i]
            in_sz = out.shape[d]
            if in_sz % o == 0:
                k = in_sz // o
                window = [1] * out.ndim
                strides = [1] * out.ndim
                window[d] = k
                strides[d] = k
                if op == "max":
                    init = -jnp.inf
                    out = jax.lax.reduce_window(out, init, jax.lax.max,
                                                window, strides, "VALID")
                else:
                    out = jax.lax.reduce_window(out, 0.0, jax.lax.add,
                                                window, strides,
                                                "VALID") / k
            else:
                # general adaptive: per-output-bin mean/max via segment ends
                starts = (np.arange(o) * in_sz) // o
                ends = ((np.arange(o) + 1) * in_sz + o - 1) // o
                pieces = []
                for s, e in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[d] = slice(int(s), int(e))
                    seg = out[tuple(sl)]
                    red = jnp.max(seg, axis=d, keepdims=True) if op == "max" \
                        else jnp.mean(seg, axis=d, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=d)
        return out
    return apply(fn, x, name=name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg", False,
                          "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format == "NHWC",
                          "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format == "NDHWC",
                          "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "max", False,
                          "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "max", False,
                          "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, "max", False,
                          "adaptive_max_pool3d")


# ---- max_unpool family (round-2 breadth) ----------------------------------

def _unpool(x, indices, n, kernel_size, stride, padding, output_size,
            data_format_first, name):
    """Scatter pooled values back to their argmax positions. ``indices``
    holds flat positions within the (spatial...) plane, the format
    max_poolNd(return_mask=True) produces."""
    x, idx = as_tensor(x), as_tensor(indices)
    kernel = _tuplize(kernel_size, n)
    stride_t = _tuplize(stride, n) or kernel
    pad_t = _tuplize(padding, n)
    if output_size is None:
        spatial_in = x.shape[2:] if data_format_first else x.shape[1:-1]
        out_sp = tuple((s - 1) * st - 2 * p + k for s, st, p, k in
                       zip(spatial_in, stride_t, pad_t, kernel))
    else:
        out_sp = tuple(int(s) for s in output_size[-n:])
    import numpy as _np
    plane = int(_np.prod(out_sp))

    def fn(a, ii):
        if not data_format_first:
            a = jnp.moveaxis(a, -1, 1)
            ii = jnp.moveaxis(ii, -1, 1)
        N, C = a.shape[:2]
        flat_v = a.reshape(N, C, -1)
        flat_i = ii.reshape(N, C, -1)
        out = jnp.zeros((N, C, plane), a.dtype)
        bidx = jnp.arange(N)[:, None, None]
        cidx = jnp.arange(C)[None, :, None]
        out = out.at[bidx, cidx, flat_i].set(flat_v)
        out = out.reshape((N, C) + out_sp)
        if not data_format_first:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply(fn, x, idx, name=name)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool(x, indices, 1, kernel_size, stride, padding,
                   output_size, data_format == "NCL", "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool(x, indices, 2, kernel_size, stride, padding,
                   output_size, data_format == "NCHW", "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool(x, indices, 3, kernel_size, stride, padding,
                   output_size, data_format == "NCDHW", "max_unpool3d")


def _max_pool_indices_nd(x, kernel, stride, padding, n, channel_last,
                         ceil_mode=False):
    """Flat spatial argmax positions for any rank (mask for unpool)."""
    import numpy as _np
    if isinstance(padding, str):
        raise NotImplementedError(
            "max_pool(return_mask=True) needs explicit int padding "
            f"(got {padding!r}); 'SAME'/'VALID' masks are unsupported")
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride, n) or kernel
    p = _tuplize(padding, n)
    a = _np.asarray(x._data)
    if channel_last:
        a = _np.moveaxis(a, -1, 1)
    N, C = a.shape[:2]
    sp = a.shape[2:]
    if ceil_mode:
        out_sp = []
        for s_, pi, k, st in zip(sp, p, kernel, stride):
            o = -(-(s_ + 2 * pi - k) // st) + 1
            if (o - 1) * st >= s_ + pi:   # window must start inside
                o -= 1
            out_sp.append(o)
        out_sp = tuple(out_sp)
    else:
        out_sp = tuple((s + 2 * pi - k) // st + 1
                       for s, pi, k, st in zip(sp, p, kernel, stride))
    # ceil_mode windows may run past the padded extent: pad the tail too
    extra = tuple(max((o - 1) * st + k - (s + 2 * pi), 0)
                  for o, st, k, s, pi in zip(out_sp, stride, kernel, sp, p))
    padded = _np.pad(a, ((0, 0), (0, 0)) +
                     tuple((pi, pi + e) for pi, e in zip(p, extra)),
                     constant_values=-_np.inf)
    idx = _np.zeros((N, C) + out_sp, _np.int64)
    for pos in _np.ndindex(*out_sp):
        sl = tuple(_np.s_[pos[d] * stride[d]:pos[d] * stride[d] + kernel[d]]
                   for d in range(n))
        win = padded[(_np.s_[:], _np.s_[:]) + sl].reshape(N, C, -1)
        am = win.argmax(-1)
        rel = _np.unravel_index(am, kernel)
        src = [_np.clip(pos[d] * stride[d] + rel[d] - p[d], 0, sp[d] - 1)
               for d in range(n)]
        flat = src[0]
        for d in range(1, n):
            flat = flat * sp[d] + src[d]
        idx[(_np.s_[:], _np.s_[:]) + pos] = flat
    return Tensor(jnp.asarray(idx))


__all__ += ["max_unpool1d", "max_unpool2d", "max_unpool3d"]


# ---- LP pooling (paddle 3.0 lp_pool1d/2d parity) --------------------------

def _lp_pool(x, norm_type, kernel_size, stride, padding, n, channel_last,
             ceil_mode, name):
    x = as_tensor(x)
    p = float(norm_type)
    kernel = _tuplize(kernel_size, n)
    count = 1
    for k in kernel:
        count *= k
    if p == float("inf"):
        return _pool(x, kernel_size, stride, padding, n, "max",
                     channel_last, ceil_mode, name=name)
    powed = apply(lambda a: jnp.power(a, p), x, name=f"{name}_pow")
    # exclusive=False: avg*count must equal the true window SUM of x^p —
    # zero-pads contribute 0 to it, so border windows must divide by the
    # full kernel count, not the valid count
    avg = _pool(powed, kernel_size, stride, padding, n, "avg",
                channel_last, ceil_mode, exclusive=False, name=name)
    return apply(lambda a: jnp.power(a * count, 1.0 / p), avg,
                 name=f"{name}_root")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """paddle.nn.functional.lp_pool1d — (sum over window of x^p)^(1/p)."""
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 1,
                    data_format == "NLC", ceil_mode, "lp_pool1d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 2,
                    data_format == "NHWC", ceil_mode, "lp_pool2d")


__all__ += ["lp_pool1d", "lp_pool2d"]


# ---- fractional max pooling (Graham 2014; paddle 2.6 parity) --------------

def _frac_starts(in_sz, out_sz, kernel, u):
    """Pseudo-random pooling-region start indices (host-side: ``u`` is a
    concrete python float, so the index grid is a compile-time constant)."""
    import math as _math

    if out_sz == 1:
        return np.zeros((1,), np.int64), in_sz
    if kernel:
        # overlapping windows of fixed size `kernel`
        alpha = (in_sz - kernel) / (out_sz - 1)
        starts = [min(int(_math.ceil(alpha * (i + u))) - 1, in_sz - kernel)
                  if i else 0 for i in range(out_sz)]
        starts = [max(0, s) for s in starts]
        return np.asarray(starts, np.int64), kernel
    # disjoint regions: boundaries a_i = ceil(alpha*(i+u)) - 1, a_0 = 0
    alpha = in_sz / out_sz
    bounds = [0]
    for i in range(1, out_sz):
        bounds.append(min(max(int(_math.ceil(alpha * (i + u))) - 1, i),
                          in_sz - (out_sz - i)))
    bounds.append(in_sz)
    starts = np.asarray(bounds[:-1], np.int64)
    widths = np.diff(np.asarray(bounds, np.int64))
    return starts, int(widths.max()), np.asarray(widths, np.int64)


def _fractional_pool(x, output_size, kernel_size, random_u, n, return_mask,
                     name):
    x = as_tensor(x)
    if random_u is None:
        from ...framework import random as framework_random
        key = framework_random.default_generator.next_key()
        random_u = float(jax.random.uniform(key))
    u = float(random_u)
    out_sz = _tuplize(output_size, n)
    kern = _tuplize(kernel_size, n) if kernel_size is not None else \
        (None,) * n
    spatial = x.shape[-n:]
    grids = []          # per dim: (index grid [out, maxw], mask [out, maxw])
    for d in range(n):
        res = _frac_starts(int(spatial[d]), int(out_sz[d]), kern[d], u)
        if len(res) == 3:
            starts, maxw, widths = res
        else:
            starts, maxw = res
            widths = np.full((len(starts),), maxw, np.int64)
        idx = starts[:, None] + np.arange(maxw)[None, :]
        mask = np.arange(maxw)[None, :] < widths[:, None]
        idx = np.clip(idx, 0, int(spatial[d]) - 1)
        grids.append((jnp.asarray(idx), jnp.asarray(mask)))

    def pool_fn(a):
        # windowed gather per spatial dim (innermost last so axis
        # numbering stays stable), mask the ragged tail, reduce
        r = a.astype(jnp.float32)
        base = r.ndim - n
        for d in range(n - 1, -1, -1):
            idx, mask = grids[d]
            r = jnp.take(r, idx, axis=base + d)   # [..., out, w, ...]
            m = mask.reshape(mask.shape + (1,) * (r.ndim - base - d - 2))
            r = jnp.where(m, r, -jnp.inf)
            r = jnp.max(r, axis=base + d + 1)
        return r.astype(a.dtype)

    out = apply(pool_fn, x, name=name)
    if not return_mask:
        return out

    def idx_fn(a):
        # same gathers, but carry each element's flat spatial coordinate
        # alongside the value and argmax-select it per window
        base = a.ndim - n
        pos = jnp.arange(int(np.prod(a.shape[base:])),
                         dtype=jnp.int32).reshape(a.shape[base:])
        rr = a.astype(jnp.float32)
        rp = jnp.broadcast_to(pos, a.shape).astype(jnp.int32)
        for d in range(n - 1, -1, -1):
            idx, mask = grids[d]
            rr = jnp.take(rr, idx, axis=base + d)
            rp = jnp.take(rp, idx, axis=base + d)
            m = mask.reshape(mask.shape + (1,) * (rr.ndim - base - d - 2))
            rr = jnp.where(m, rr, -jnp.inf)
            am = jnp.argmax(rr, axis=base + d + 1, keepdims=True)
            rr = jnp.squeeze(jnp.take_along_axis(rr, am, base + d + 1),
                             base + d + 1)
            rp = jnp.squeeze(jnp.take_along_axis(rp, am, base + d + 1),
                             base + d + 1)
        return rp

    idx_t = apply(idx_fn, x, name=f"{name}_mask", differentiable=False)
    return out, idx_t


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """paddle.nn.functional.fractional_max_pool2d — pseudo-random pooling
    regions (Graham, "Fractional Max-Pooling")."""
    return _fractional_pool(x, output_size, kernel_size, random_u, 2,
                            return_mask, "fractional_max_pool2d")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    return _fractional_pool(x, output_size, kernel_size, random_u, 3,
                            return_mask, "fractional_max_pool3d")


__all__ += ["fractional_max_pool2d", "fractional_max_pool3d"]
