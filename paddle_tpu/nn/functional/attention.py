"""Attention functionals.

``scaled_dot_product_attention`` mirrors paddle's API
(python/paddle/nn/functional/flash_attention.py, UNVERIFIED) and routes to
the Pallas flash-attention kernel on TPU (SURVEY.md §2.1: fused_attention /
flash-attn integration → Pallas), with a jnp reference path everywhere else.
Layout convention is paddle's: [batch, seq, num_heads, head_dim]."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...framework import flags
from ...ops.common import as_tensor

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "sdpa_reference", "sdpa_with_cache"]


def _use_pallas() -> bool:
    return (flags.flag("FLAGS_enable_pallas_kernels")
            and jax.default_backend() == "tpu")


def sdpa_reference(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
                   scale=None, dropout_key=None):
    """Pure-jnp reference attention on [B, S, H, D] arrays."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # GQA/MQA: repeat kv heads up to the query head count
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, jnp.asarray(-1e30, logits.dtype))
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits,
                               jnp.asarray(-1e30, logits.dtype))
        else:
            logits = logits + attn_mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    probs = probs.astype(v.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1 - dropout_p, probs.shape)
        probs = probs * keep / (1 - dropout_p)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Inputs [batch, seq, num_heads, head_dim] (paddle convention)."""
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    from ...amp.auto_cast import maybe_cast_matmul
    q, k = maybe_cast_matmul(q, k)
    _, v = maybe_cast_matmul(q, v)
    args = [q, k, v]
    if attn_mask is not None:
        args.append(as_tensor(attn_mask))

    use_pallas = (_use_pallas() and attn_mask is None and dropout_p == 0.0
                  and q.shape[1] == k.shape[1])
    if use_pallas:
        from jax import ad_checkpoint

        from ...ops.pallas import flash_attention as fa

        def fn(qq, kk, vv):
            out = fa.flash_attention(qq, kk, vv, causal=is_causal)
            # name the kernel output so the opt-in remat policy
            # FLAGS_recompute_policy='dots_and_flash_saveable' can save
            # it (under dots_saveable a checkpointed layer re-runs the
            # flash forward in backward — it is not a dot)
            return ad_checkpoint.checkpoint_name(out, "flash_out")
        return apply(fn, q, k, v, name="flash_attention")

    key_rng = None
    if dropout_p > 0.0 and training:
        from ...framework import random as fr
        key_rng = fr.default_generator.next_key()

    def fn(qq, kk, vv, *m):
        return sdpa_reference(qq, kk, vv, m[0] if m else None,
                              dropout_p if key_rng is not None else 0.0,
                              is_causal, dropout_key=key_rng)
    return apply(fn, *args, name="sdpa")


def sdpa_with_cache(query, key, value, k_cache, v_cache, pos):
    """Incremental-decoding attention over a static-shape KV cache.

    Writes ``key``/``value`` (new tokens, [B, S, KV, D]) into the caches
    ([B, max_len, KV, D]) at sequence offset ``pos`` (int32 scalar tensor,
    traceable), then attends ``query`` over the whole cache with the
    positional causal mask ``cache_index <= pos + query_index``. Covers both
    prefill (S = prompt len, pos = 0) and decode (S = 1, pos = current len)
    uniformly. Role of the reference's decoder ``cache_kv`` path in
    fused_multi_head_attention / PaddleNLP decoding (mount empty, no cites).

    Returns ``(out, new_k_cache, new_v_cache)``.
    """
    q = as_tensor(query)
    k, v = as_tensor(key), as_tensor(value)
    kc, vc = as_tensor(k_cache), as_tensor(v_cache)
    p = as_tensor(pos)

    def fn(qq, kk, vv, kcache, vcache, pp):
        pp = pp.astype(jnp.int32)
        start = (jnp.zeros((), jnp.int32), pp,
                 jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
        kcache = jax.lax.dynamic_update_slice(
            kcache, kk.astype(kcache.dtype), start)
        vcache = jax.lax.dynamic_update_slice(
            vcache, vv.astype(vcache.dtype), start)
        s, max_len = qq.shape[1], kcache.shape[1]
        mask = (jnp.arange(max_len)[None, :]
                <= pp + jnp.arange(s)[:, None])          # [S, max_len]
        out = sdpa_reference(qq, kcache.astype(qq.dtype),
                             vcache.astype(qq.dtype),
                             attn_mask=mask[None, None])
        return out, kcache, vcache

    return apply(fn, q, k, v, kc, vc, p, n_outputs=3, name="sdpa_cached")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(query, key, value, None, dropout,
                                       causal, training)
    if return_softmax:
        return out, None
    return out, None


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention with a CSR sparsity pattern
    (paddle.nn.functional.sparse_attention parity). q/k/v:
    [B, H, S, D]; offset [B, H, S+1], columns [B, H, nnz] — row i of the
    attention matrix only attends to the listed columns.

    TPU formulation: a dense masked softmax built FROM the CSR pattern
    (scatter of the column lists into a [S, S] mask) — on TPU the MXU
    prefers the dense masked matmul over gather-based sparsity at these
    block sizes; the CSR arguments keep the reference's contract."""
    import jax
    import jax.numpy as jnp

    from ...framework.core import apply
    from ...ops.common import as_tensor

    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    off, cols = as_tensor(sparse_csr_offset), as_tensor(sparse_csr_columns)

    def fn(qq, kk, vv, offsets, columns, *rest):
        import math as _math
        b, h, s, d = qq.shape
        nnz = columns.shape[-1]

        def one_mask(off1, col1):
            # row id of every nnz entry from the CSR offsets
            counts = off1[1:] - off1[:-1]               # [S]
            rows = jnp.repeat(jnp.arange(s), counts.astype(jnp.int32),
                              total_repeat_length=nnz)
            m = jnp.zeros((s, s), jnp.bool_)
            return m.at[rows, col1.astype(jnp.int32)].set(True)

        mask = jax.vmap(jax.vmap(one_mask))(offsets, columns)  # [B,H,S,S]
        logits = jnp.einsum("bhqd,bhkd->bhqk", qq, kk,
                            preferred_element_type=jnp.float32)
        logits = logits / _math.sqrt(d)
        if rest:
            logits = logits + rest[0].astype(logits.dtype)
        logits = jnp.where(mask, logits, -1e30)
        p = jax.nn.softmax(logits, -1).astype(vv.dtype)
        # rows with an empty pattern must output zeros, not uniform noise
        any_row = mask.any(-1, keepdims=True)
        p = p * any_row.astype(p.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, vv)

    args = [q, k, v, off, cols]
    if attn_mask is not None:
        args.append(as_tensor(attn_mask))
    return apply(fn, *args, name="sparse_attention")


__all__ += ["sparse_attention"]
