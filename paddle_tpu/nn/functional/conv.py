"""Convolution functionals over ``jax.lax.conv_general_dilated`` — the MXU
path for convs (python/paddle/nn/functional/conv.py parity, UNVERIFIED)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply
from ...ops.common import as_tensor

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(x) for x in v)
    if len(v) == 1:
        return v * n
    return v


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    # paddle also accepts [[0,0],[0,0],[p,p],...] including batch/channel
    return [tuple(p) for p in padding[-n:]]


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, name):
    x, weight = as_tensor(x), as_tensor(weight)
    from ...amp.auto_cast import maybe_cast_matmul
    x, weight = maybe_cast_matmul(x, weight)
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    pad = _padding(padding, n)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    sp = "DHW"[3 - n:]
    if channel_last:
        dn = ("N" + sp + "C", "OI" + sp, "N" + sp + "C")
    else:
        dn = ("NC" + sp, "OI" + sp, "NC" + sp)

    def fn(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if b:
            bia = b[0].astype(out.dtype)
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = bia.shape[0]
            out = out + bia.reshape(shape)
        return out
    if bias is not None:
        return apply(fn, x, weight, as_tensor(bias), name=name)
    return apply(fn, x, weight, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NWC" if data_format == "NLC" else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 fmt, name="conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, name="conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, name="conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, n, data_format, output_size, name):
    x, weight = as_tensor(x), as_tensor(weight)
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    opad = _tuplize(output_padding, n)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    sp = "DHW"[3 - n:]
    if channel_last:
        dn = ("N" + sp + "C", "IO" + sp, "N" + sp + "C")
    else:
        dn = ("NC" + sp, "IO" + sp, "NC" + sp)
    if isinstance(padding, str):
        pads = padding.upper()
    else:
        pads = _padding(padding, n)

    def fn(a, w, *b):
        # paddle conv_transpose weight: [in, out/groups, *k]; lax wants
        # gradient-style transposed conv: use conv_transpose with IO spec.
        if isinstance(pads, str):
            jpad = pads
        else:
            # transposed conv padding: effective pad = dilation*(k-1) - pad
            k = w.shape[2:]
            jpad = [(dilation[i] * (k[i] - 1) - pads[i][0],
                     dilation[i] * (k[i] - 1) - pads[i][1] + opad[i])
                    for i in range(n)]
        if groups == 1:
            out = jax.lax.conv_general_dilated(
                a, w, window_strides=(1,) * n, padding=jpad,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=dn)
        else:
            ch_ax = a.ndim - 1 if channel_last else 1
            xs = jnp.split(a, groups, axis=ch_ax)
            ws = jnp.split(w, groups, axis=0)
            outs = [jax.lax.conv_general_dilated(
                xg, wg, window_strides=(1,) * n, padding=jpad,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=dn) for xg, wg in zip(xs, ws)]
            out = jnp.concatenate(outs, axis=ch_ax)
        if b:
            bia = b[0].astype(out.dtype)
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = bia.shape[0]
            out = out + bia.reshape(shape)
        return out
    # weight layout: paddle is [in, out/groups, *k]; lax IO spec means
    # dim0=I, dim1=O which matches directly.
    def fn_flip(a, w, *b):
        w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        return fn(a, w, *b)
    if bias is not None:
        return apply(fn_flip, x, weight, as_tensor(bias), name=name)
    return apply(fn_flip, x, weight, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    fmt = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, fmt, output_size,
                           "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size,
                           "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size,
                           "conv3d_transpose")
