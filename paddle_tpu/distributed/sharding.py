"""``paddle.distributed.sharding`` namespace (upstream
python/paddle/distributed/sharding/, UNVERIFIED) — re-exports the fleet
group-sharded implementations."""

from .fleet.sharding import (group_sharded_parallel, GroupShardedStage3,
                             DygraphShardingOptimizer, shard_array_over)

__all__ = ["group_sharded_parallel", "GroupShardedStage3",
           "DygraphShardingOptimizer", "shard_array_over"]


def save_group_sharded_model(model, output, optimizer=None):
    """Save a group-sharded model (upstream parity): gathers shards are
    NamedSharding-backed, so a plain state_dict save is already global."""
    import os

    from ..framework.io import save

    os.makedirs(output, exist_ok=True)
    target = model._layer if hasattr(model, "_layer") else model
    save(target.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))


__all__.append("save_group_sharded_model")
