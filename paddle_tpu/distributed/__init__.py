"""``paddle.distributed`` — the distributed stack over a named TPU mesh
(SURVEY.md §2.3: DP / sharding 1-3 / TP / PP / SP / CP(ring+Ulysses) / EP,
hybrid-composed).

Data plane = XLA collectives over ICI/DCN inside compiled programs (GSPMD or
shard_map); control plane = jax.distributed. The fleet/communication APIs
keep Paddle's shape for source familiarity."""

from .env import (init_parallel_env, get_rank, get_world_size,
                  is_initialized, ParallelEnv)
from .mesh import (ProcessMesh, Shard, Replicate, Partial, Placement,
                   shard_tensor, reshard, dtensor_from_fn, shard_layer,
                   shard_op, get_mesh, set_mesh, auto_mesh,
                   shard_optimizer)
from .communication import (all_reduce, all_gather, all_gather_object,
                            reduce_scatter, alltoall, alltoall_single,
                            broadcast, broadcast_object_list, reduce, scatter,
                            send, recv, isend, irecv, barrier, new_group,
                            get_group, wait, ReduceOp, P2POp,
                            batch_isend_irecv, stream, gather,
                            scatter_object_list, destroy_process_group,
                            get_backend, is_available)
from .parallel import DataParallel
from . import fleet
from . import checkpoint
from .checkpoint.save_load import (save_state_dict, load_state_dict)
from .parallel_layers import (ColumnParallelLinear, RowParallelLinear,
                              VocabParallelEmbedding, ParallelCrossEntropy,
                              split)
from .auto_parallel_api import (to_static, Strategy,
                                DistAttr, DistModel, unshard_dtensor)
from . import launch  # noqa: F401
from . import passes  # noqa: F401
from .zero_bubble import (run_pipeline_train, make_schedule)
from ..native import TCPStore  # noqa: F401 — rendezvous control plane
from . import rpc  # noqa: F401 — control-plane RPC (init_rpc/rpc_sync/...)
from . import sharding  # noqa: F401 — group_sharded_parallel namespace
from . import utils  # noqa: F401
from .spawn_api import spawn
from .parallelize import (parallelize, ColWiseParallel, RowWiseParallel,
                          PrepareLayerInput, PrepareLayerOutput)
from .ps_dataset import QueueDataset, InMemoryDataset


def gloo_barrier():
    """Host-side barrier (the Gloo-role control-plane sync)."""
    barrier()


__all__ = [
    "spawn", "gather", "scatter_object_list",
    "destroy_process_group", "get_backend", "is_available",
    "parallelize", "ColWiseParallel", "RowWiseParallel",
    "init_parallel_env", "get_rank", "get_world_size", "is_initialized",
    "ParallelEnv", "ProcessMesh", "Shard", "Replicate", "Partial",
    "shard_tensor", "reshard", "dtensor_from_fn", "shard_layer", "get_mesh",
    "set_mesh", "auto_mesh", "all_reduce", "all_gather", "all_gather_object",
    "reduce_scatter", "alltoall", "alltoall_single", "broadcast",
    "broadcast_object_list", "reduce", "scatter", "send", "recv", "isend",
    "irecv", "barrier", "new_group", "get_group", "wait", "ReduceOp",
    "P2POp", "batch_isend_irecv", "DataParallel", "fleet", "checkpoint",
    "save_state_dict", "load_state_dict", "ColumnParallelLinear",
    "RowParallelLinear", "VocabParallelEmbedding", "ParallelCrossEntropy",
    "Strategy", "DistAttr", "DistModel", "unshard_dtensor", "stream",
    "run_pipeline_train", "make_schedule", "Placement", "shard_optimizer",
    "split", "QueueDataset", "InMemoryDataset", "gloo_barrier",
]
