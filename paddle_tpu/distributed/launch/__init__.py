"""``paddle.distributed.launch`` — multi-host launcher
(python/paddle/distributed/launch/ parity, UNVERIFIED).

``python -m paddle_tpu.distributed.launch [--nnodes N] [--master ip:port]
train.py args...`` — spawns one process per node (TPU: one process per host
drives all local chips; contrast GPU's one-proc-per-device), sets the
``PADDLE_*`` env contract, captures per-rank logs, restarts on failure
(elastic checkpoint-restart, SURVEY.md §5)."""

from .main import launch_main

__all__ = ["launch_main"]
