"""Launcher implementation (fleetrun parity).

On TPU pods each host runs ONE process that drives its local chips; the
launcher therefore spawns `nproc_per_node` processes only for CPU-simulated
multi-process testing (the Gloo-fallback role, SURVEY.md §4), and for real
pods simply execs the training script with the coordination env set.

Elastic fault tolerance (the launcher-side half; workers implement the
other half via ``fleet.elastic.PreemptionGuard`` + topology-aware
checkpoints):

- **failure detection** distinguishes three worker fates: a *clean
  preemption* (exit ``PREEMPTED_EXIT_CODE`` = 75 after a committed
  emergency checkpoint) relaunches on its own budget
  (``--max_preempt_restarts``); a *crash* (any other nonzero exit) or
  a *stale heartbeat* (hung worker) burns ``--max_restarts``;
- **auto-restart** respawns with the *surviving* world size when
  ``--min_nproc_per_node`` allows shrinking (crashed ranks are assumed
  gone with their capacity), resolves the newest COMMITTED checkpoint
  and exports it as ``PADDLE_RESUME_CHECKPOINT`` — the workers reshard
  it onto the reduced mesh — plus ``PADDLE_RESTART_ROUND`` so trainers
  can derive round-dependent config;
- restarts use exponential backoff (``--elastic_timeout`` base,
  ``--max_backoff`` cap) and hard budgets, so a permanently-broken
  fleet fails loudly instead of hanging or tight-looping;
- a SIGTERM delivered to the launcher itself (the preemptor reclaiming
  the whole node) is forwarded to every worker, which gets the grace
  window to emergency-checkpoint; the launcher then exits 75 without
  relaunching."""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch_main"]

#: clean-preemption worker exit code (fleet.elastic.PREEMPTED_EXIT_CODE;
#: duplicated literally so the launcher never imports jax-adjacent code)
PREEMPTED_EXIT_CODE = 75


def _parse():
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="coordinator ip:port")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (CPU-simulation/testing only; "
                        "TPU uses 1 process per host)")
    p.add_argument("--min_nproc_per_node", type=int, default=0,
                   help="if > 0, a crashed rank's slot is treated as "
                        "lost and the next round respawns with the "
                        "surviving world size, never below this floor "
                        "(0 = always respawn at full size). Single-"
                        "node only: per-launcher shrinking is "
                        "uncoordinated across nodes, so with "
                        "--nnodes > 1 it is ignored with a warning")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--devices", default=None)
    p.add_argument("--max_restarts", type=int,
                   default=int(os.environ.get("PADDLE_MAX_RESTARTS", "0")))
    p.add_argument("--max_preempt_restarts", type=int,
                   default=int(os.environ.get(
                       "PADDLE_MAX_PREEMPT_RESTARTS", "16")),
                   help="separate budget for clean preemptions (exit "
                        "code 75): routine fleet churn must not burn "
                        "the crash budget, but still needs a bound")
    p.add_argument("--elastic_timeout", type=int, default=30,
                   help="base restart delay; doubles every consecutive "
                        "restart up to --max_backoff")
    p.add_argument("--max_backoff", type=float, default=300.0)
    p.add_argument("--grace", type=float,
                   default=float(os.environ.get("PADDLE_PREEMPT_GRACE_S",
                                                "30")),
                   help="seconds workers get between the launcher's "
                        "SIGTERM forward and SIGKILL (the emergency-"
                        "checkpoint window)")
    p.add_argument("--heartbeat_timeout", type=float, default=0.0,
                   help="if > 0, watch worker heartbeats (workers call "
                        "fleet.elastic.start_heartbeat) and treat a "
                        "stale rank as a fault -> kill + relaunch")
    p.add_argument("--checkpoint_dir",
                   default=os.environ.get("PADDLE_CHECKPOINT_DIR"),
                   help="checkpoint root holding step_N dirs; each "
                        "(re)launch round resolves the newest COMMITTED "
                        "checkpoint (torn saves skipped) and exports it "
                        "to workers as PADDLE_RESUME_CHECKPOINT / "
                        "PADDLE_RESUME_STEP")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _spawn(rank, world, args, extra_env=None):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_RANK": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_NNODES": str(world),
        "PADDLE_WORLD_SIZE": str(world),
    })
    if args.master:
        env["PADDLE_MASTER"] = args.master
        env.setdefault("MASTER_ADDR", args.master.split(":")[0])
        if ":" in args.master:
            env.setdefault("MASTER_PORT", args.master.split(":")[1])
    if extra_env:
        env.update(extra_env)
    os.makedirs(args.log_dir, exist_ok=True)
    log_path = os.path.join(args.log_dir, f"workerlog.{rank}")
    logf = open(log_path, "a")
    proc = subprocess.Popen([sys.executable, args.script] +
                            args.script_args, env=env, stdout=logf,
                            stderr=subprocess.STDOUT)
    return proc, logf


def _dump_worker_log(args, local, ret, logf, tail_lines=40):
    """Surface the failing rank's log tail on the launcher's stderr —
    the observability contract of the reference launcher (a failure must
    be diagnosable without hunting for workerlog files)."""
    logf.flush()
    path = os.path.join(args.log_dir, f"workerlog.{local}")
    print(f"paddle_tpu.launch: rank {local} exited rc={ret}; "
          f"last {tail_lines} lines of {path}:", file=sys.stderr)
    try:
        with open(path) as f:
            for line in f.readlines()[-tail_lines:]:
                print(f"  [rank {local}] {line.rstrip()}", file=sys.stderr)
    except OSError as e:
        print(f"  (log unreadable: {e})", file=sys.stderr)


def _terminate_all(procs, grace=5.0):
    for proc, _ in procs:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
    end = time.time() + grace
    for proc, logf in procs:
        try:
            proc.wait(timeout=max(0.1, end - time.time()))
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        logf.close()


def _run_round(procs, args, manager, shutdown):
    """Poll all workers concurrently (a failed or hung rank must be
    noticed while others still run — the fault-watch role of the
    reference's elastic manager). Returns (outcome, bad_ranks):
    outcome 'ok' | 'failed' | 'stale' | 'preempted' | 'shutdown';
    bad_ranks names the crashed/stale local ranks (the slots a
    shrinking relaunch treats as lost)."""
    start = time.time()
    # a worker hung *before* it ever heartbeats must also be caught:
    # give registration a bounded grace window
    register_deadline = start + max(5 * args.heartbeat_timeout, 30.0)
    while True:
        if shutdown["flag"]:
            return "shutdown", []
        alive = False
        done_ok = set()
        preempted = []
        failed = []
        for local, (proc, logf) in enumerate(procs):
            ret = proc.poll()
            if ret is None:
                alive = True
            elif ret == PREEMPTED_EXIT_CODE:
                # clean preemption: the worker drained and committed an
                # emergency checkpoint before exiting — not a crash
                preempted.append(local)
            elif ret != 0:
                # keep scanning: simultaneous multi-rank deaths must
                # ALL be counted, or a shrinking relaunch underestimates
                # the lost capacity and crashes again
                _dump_worker_log(args, local, ret, logf)
                failed.append(local)
            else:
                done_ok.add(local)
        if failed:
            return "failed", failed
        if preempted:
            # a PARTIAL preemption must end the round too: peers of a
            # preempted rank would otherwise block forever at their
            # next collective (still heartbeating, so the watchdog
            # cannot fire). _terminate_all SIGTERMs the survivors —
            # each gets the grace window to emergency-checkpoint.
            print(f"paddle_tpu.launch: ranks {preempted} exited on "
                  f"clean preemption (rc={PREEMPTED_EXIT_CODE})",
                  file=sys.stderr)
            if not alive:
                for _, logf in procs:
                    logf.close()
            return "preempted", []
        if not alive:
            for _, logf in procs:
                logf.close()
            return "ok", []
        if manager is not None:
            from ..fleet.elastic import ElasticStatus
            # cleanly-exited workers stop heartbeating legitimately
            # (a preempted rank ended the round above already)
            status, bad = manager.watch(ignore=done_ok)
            if status is ElasticStatus.STALE:
                print(f"paddle_tpu.launch: stale heartbeats from ranks "
                      f"{bad}", file=sys.stderr)
                return "stale", list(bad)
            if (status is ElasticStatus.INCOMPLETE
                    and time.time() > register_deadline):
                print(f"paddle_tpu.launch: ranks {bad} never "
                      f"registered a heartbeat", file=sys.stderr)
                return "stale", list(bad)
        time.sleep(0.2)


def launch_main():
    args = _parse()
    if args.min_nproc_per_node > 0 and args.nnodes > 1:
        # each node's launcher only sees its own workers: independent
        # shrinking would leave the nodes disagreeing on world size
        # and rank assignment — refuse rather than misaddress ranks
        print("paddle_tpu.launch: --min_nproc_per_node is single-node "
              "only (per-launcher shrinking is uncoordinated across "
              "nodes); ignoring it", file=sys.stderr)
        args.min_nproc_per_node = 0
    restarts = 0
    preempt_restarts = 0
    nproc = args.nproc_per_node
    manager = None
    hb_dir = None
    # forward a preemption of the launcher itself: SIGTERM fans out to
    # the workers (each gets the grace window to emergency-checkpoint),
    # then the launcher exits 75 instead of relaunching
    shutdown = {"flag": False}

    def _on_term(signum, frame):
        shutdown["flag"] = True

    try:
        prev_term = signal.signal(signal.SIGTERM, _on_term)
    except ValueError:      # not the main thread (embedded use)
        prev_term = None
    if args.heartbeat_timeout > 0:
        from ..fleet.elastic import ElasticManager
        hb_dir = os.path.join(args.log_dir, "heartbeat")
        os.makedirs(hb_dir, exist_ok=True)
        # watch only this node's ranks; peer nodes watch their own
        manager = ElasticManager(nproc, directory=hb_dir,
                                 timeout=args.heartbeat_timeout)
    try:
        while True:
            procs = []
            world = args.nnodes * nproc
            base = args.rank * nproc
            if manager is not None:
                manager.world_size = nproc
                manager.reset()
            resume_env = {
                "PADDLE_RESTART_ROUND":
                    str(restarts + preempt_restarts),
                "PADDLE_PREEMPT_GRACE_S": str(args.grace),
            }
            if args.checkpoint_dir:
                # validated auto-resume: point workers at the newest
                # COMMITTED checkpoint; a save torn by the previous
                # crash is skipped, so restart recovers the last good
                # step — and the workers reshard it onto whatever mesh
                # the surviving world builds
                from ..fleet.elastic import (latest_valid_checkpoint,
                                             checkpoint_step)
                ck = latest_valid_checkpoint(args.checkpoint_dir)
                if ck is not None:
                    resume_env.update({
                        "PADDLE_RESUME_CHECKPOINT": ck,
                        "PADDLE_RESUME_STEP": str(checkpoint_step(ck)),
                    })
                    print(f"paddle_tpu.launch: resuming from {ck}")
            for local in range(nproc):
                rank = base + local
                extra = {"PADDLE_LOCAL_RANK": str(local)}
                extra.update(resume_env)
                if hb_dir is not None:
                    extra["PADDLE_ELASTIC_HEARTBEAT_DIR"] = hb_dir
                    extra["PADDLE_ELASTIC_HEARTBEAT_RANK"] = str(local)
                if nproc > 1:
                    # CPU-simulated cluster: isolate each proc onto CPU
                    extra["JAX_PLATFORMS"] = "cpu"
                procs.append(_spawn(rank, world, args, extra))
            try:
                outcome, bad = _run_round(procs, args, manager, shutdown)
            except KeyboardInterrupt:
                _terminate_all(procs)
                raise
            if outcome == "ok":
                print("paddle_tpu.launch: all workers exited cleanly")
                return 0
            if outcome == "shutdown":
                print("paddle_tpu.launch: SIGTERM received; forwarding "
                      f"to workers with a {args.grace}s grace window",
                      file=sys.stderr)
                _terminate_all(procs, grace=args.grace)
                return PREEMPTED_EXIT_CODE
            _terminate_all(procs, grace=args.grace)
            # failure detection → checkpoint-restart (elastic mode):
            # clean preemptions relaunch on their own budget; crashes
            # and hangs burn the crash budget and may shrink the world
            if outcome == "preempted":
                if preempt_restarts >= args.max_preempt_restarts:
                    print("paddle_tpu.launch: preempt restarts "
                          "exhausted", file=sys.stderr)
                    return 1
                preempt_restarts += 1
                budget = f"preempt {preempt_restarts}/" \
                         f"{args.max_preempt_restarts}"
            else:
                if restarts >= args.max_restarts:
                    print(f"paddle_tpu.launch: worker {outcome}; "
                          f"restarts exhausted", file=sys.stderr)
                    return 1
                restarts += 1
                budget = f"{restarts}/{args.max_restarts}"
                if args.min_nproc_per_node > 0 and bad:
                    survivors = max(args.min_nproc_per_node,
                                    nproc - len(bad))
                    if survivors != nproc:
                        print(f"paddle_tpu.launch: shrinking "
                              f"nproc_per_node {nproc} -> {survivors} "
                              f"(lost ranks {bad})", file=sys.stderr)
                        nproc = survivors
            delay = _backoff(args, restarts + preempt_restarts)
            print(f"paddle_tpu.launch: worker {outcome}; relaunching "
                  f"({budget}) after {delay:.0f}s", file=sys.stderr)
            _interruptible_sleep(delay, shutdown)
            if shutdown["flag"]:
                return PREEMPTED_EXIT_CODE
    finally:
        if prev_term is not None:
            try:
                signal.signal(signal.SIGTERM, prev_term)
            except (ValueError, TypeError):
                pass


def _backoff(args, attempt):
    """Exponential backoff: base * 2^(attempt-1) capped at
    --max_backoff (a zero base stays zero — the test fast path)."""
    base = float(args.elastic_timeout)
    if base <= 0:
        return 0.0
    return min(base * (2.0 ** max(0, attempt - 1)), args.max_backoff)


def _interruptible_sleep(seconds, shutdown):
    end = time.time() + seconds
    while time.time() < end and not shutdown["flag"]:
        time.sleep(min(0.2, max(0.0, end - time.time())))


if __name__ == "__main__":
    sys.exit(launch_main())
