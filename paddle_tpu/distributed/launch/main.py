"""Launcher implementation (fleetrun parity).

On TPU pods each host runs ONE process that drives its local chips; the
launcher therefore spawns `nproc_per_node` processes only for CPU-simulated
multi-process testing (the Gloo-fallback role, SURVEY.md §4), and for real
pods simply execs the training script with the coordination env set."""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch_main"]


def _parse():
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="coordinator ip:port")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (CPU-simulation/testing only; "
                        "TPU uses 1 process per host)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--devices", default=None)
    p.add_argument("--max_restarts", type=int,
                   default=int(os.environ.get("PADDLE_MAX_RESTARTS", "0")))
    p.add_argument("--elastic_timeout", type=int, default=30)
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _spawn(rank, world, args, extra_env=None):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_RANK": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_NNODES": str(world),
        "PADDLE_WORLD_SIZE": str(world),
    })
    if args.master:
        env["PADDLE_MASTER"] = args.master
        env.setdefault("MASTER_ADDR", args.master.split(":")[0])
        if ":" in args.master:
            env.setdefault("MASTER_PORT", args.master.split(":")[1])
    if extra_env:
        env.update(extra_env)
    os.makedirs(args.log_dir, exist_ok=True)
    log_path = os.path.join(args.log_dir, f"workerlog.{rank}")
    logf = open(log_path, "a")
    proc = subprocess.Popen([sys.executable, args.script] +
                            args.script_args, env=env, stdout=logf,
                            stderr=subprocess.STDOUT)
    return proc, logf


def launch_main():
    args = _parse()
    world = args.nnodes * args.nproc_per_node
    restarts = 0
    while True:
        procs = []
        base = args.rank * args.nproc_per_node
        for local in range(args.nproc_per_node):
            rank = base + local
            extra = {}
            if args.nproc_per_node > 1:
                # CPU-simulated cluster: isolate each proc onto CPU devices
                extra["JAX_PLATFORMS"] = "cpu"
            procs.append(_spawn(rank, world, args, extra))
        failed = False
        try:
            for proc, logf in procs:
                ret = proc.wait()
                logf.close()
                if ret != 0:
                    failed = True
        except KeyboardInterrupt:
            for proc, _ in procs:
                proc.send_signal(signal.SIGTERM)
            raise
        if not failed:
            print("paddle_tpu.launch: all workers exited cleanly")
            return 0
        # failure detection → checkpoint-restart (elastic mode)
        if restarts >= args.max_restarts:
            print("paddle_tpu.launch: worker failed; restarts exhausted",
                  file=sys.stderr)
            return 1
        restarts += 1
        print(f"paddle_tpu.launch: worker failed; relaunching "
              f"({restarts}/{args.max_restarts}) after "
              f"{args.elastic_timeout}s", file=sys.stderr)
        time.sleep(args.elastic_timeout)


if __name__ == "__main__":
    sys.exit(launch_main())
