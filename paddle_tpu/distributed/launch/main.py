"""Launcher implementation (fleetrun parity).

On TPU pods each host runs ONE process that drives its local chips; the
launcher therefore spawns `nproc_per_node` processes only for CPU-simulated
multi-process testing (the Gloo-fallback role, SURVEY.md §4), and for real
pods simply execs the training script with the coordination env set."""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch_main"]


def _parse():
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="coordinator ip:port")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (CPU-simulation/testing only; "
                        "TPU uses 1 process per host)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--devices", default=None)
    p.add_argument("--max_restarts", type=int,
                   default=int(os.environ.get("PADDLE_MAX_RESTARTS", "0")))
    p.add_argument("--elastic_timeout", type=int, default=30)
    p.add_argument("--heartbeat_timeout", type=float, default=0.0,
                   help="if > 0, watch worker heartbeats (workers call "
                        "fleet.elastic.start_heartbeat) and treat a "
                        "stale rank as a fault -> kill + relaunch")
    p.add_argument("--checkpoint_dir",
                   default=os.environ.get("PADDLE_CHECKPOINT_DIR"),
                   help="checkpoint root holding step_N dirs; each "
                        "(re)launch round resolves the newest COMMITTED "
                        "checkpoint (torn saves skipped) and exports it "
                        "to workers as PADDLE_RESUME_CHECKPOINT / "
                        "PADDLE_RESUME_STEP")
    p.add_argument("script", help="training script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _spawn(rank, world, args, extra_env=None):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_RANK": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_NNODES": str(world),
        "PADDLE_WORLD_SIZE": str(world),
    })
    if args.master:
        env["PADDLE_MASTER"] = args.master
        env.setdefault("MASTER_ADDR", args.master.split(":")[0])
        if ":" in args.master:
            env.setdefault("MASTER_PORT", args.master.split(":")[1])
    if extra_env:
        env.update(extra_env)
    os.makedirs(args.log_dir, exist_ok=True)
    log_path = os.path.join(args.log_dir, f"workerlog.{rank}")
    logf = open(log_path, "a")
    proc = subprocess.Popen([sys.executable, args.script] +
                            args.script_args, env=env, stdout=logf,
                            stderr=subprocess.STDOUT)
    return proc, logf


def _dump_worker_log(args, local, ret, logf, tail_lines=40):
    """Surface the failing rank's log tail on the launcher's stderr —
    the observability contract of the reference launcher (a failure must
    be diagnosable without hunting for workerlog files)."""
    logf.flush()
    path = os.path.join(args.log_dir, f"workerlog.{local}")
    print(f"paddle_tpu.launch: rank {local} exited rc={ret}; "
          f"last {tail_lines} lines of {path}:", file=sys.stderr)
    try:
        with open(path) as f:
            for line in f.readlines()[-tail_lines:]:
                print(f"  [rank {local}] {line.rstrip()}", file=sys.stderr)
    except OSError as e:
        print(f"  (log unreadable: {e})", file=sys.stderr)


def _terminate_all(procs, grace=5.0):
    for proc, _ in procs:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
    end = time.time() + grace
    for proc, logf in procs:
        try:
            proc.wait(timeout=max(0.1, end - time.time()))
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        logf.close()


def _run_round(procs, args, manager):
    """Poll all workers concurrently (a failed or hung rank must be
    noticed while others still run — the fault-watch role of the
    reference's elastic manager). Returns 'ok' | 'failed' | 'stale'."""
    start = time.time()
    # a worker hung *before* it ever heartbeats must also be caught:
    # give registration a bounded grace window
    register_deadline = start + max(5 * args.heartbeat_timeout, 30.0)
    while True:
        alive = False
        done_ok = set()
        for local, (proc, logf) in enumerate(procs):
            ret = proc.poll()
            if ret is None:
                alive = True
            elif ret != 0:
                _dump_worker_log(args, local, ret, logf)
                return "failed"
            else:
                done_ok.add(local)
        if not alive:
            for _, logf in procs:
                logf.close()
            return "ok"
        if manager is not None:
            from ..fleet.elastic import ElasticStatus
            # cleanly-exited workers stop heartbeating legitimately
            status, bad = manager.watch(ignore=done_ok)
            if status is ElasticStatus.STALE:
                print(f"paddle_tpu.launch: stale heartbeats from ranks "
                      f"{bad}", file=sys.stderr)
                return "stale"
            if (status is ElasticStatus.INCOMPLETE
                    and time.time() > register_deadline):
                print(f"paddle_tpu.launch: ranks {bad} never "
                      f"registered a heartbeat", file=sys.stderr)
                return "stale"
        time.sleep(0.2)


def launch_main():
    args = _parse()
    world = args.nnodes * args.nproc_per_node
    restarts = 0
    manager = None
    hb_dir = None
    if args.heartbeat_timeout > 0:
        from ..fleet.elastic import ElasticManager
        hb_dir = os.path.join(args.log_dir, "heartbeat")
        os.makedirs(hb_dir, exist_ok=True)
        # watch only this node's ranks; peer nodes watch their own
        manager = ElasticManager(args.nproc_per_node, directory=hb_dir,
                                 timeout=args.heartbeat_timeout)
    while True:
        procs = []
        base = args.rank * args.nproc_per_node
        if manager is not None:
            manager.reset()
        resume_env = {}
        if args.checkpoint_dir:
            # validated auto-resume: point workers at the newest
            # COMMITTED checkpoint; a save torn by the previous crash
            # is skipped, so restart recovers the last good step
            from ..fleet.elastic import (latest_valid_checkpoint,
                                         checkpoint_step)
            ck = latest_valid_checkpoint(args.checkpoint_dir)
            if ck is not None:
                resume_env = {
                    "PADDLE_RESUME_CHECKPOINT": ck,
                    "PADDLE_RESUME_STEP": str(checkpoint_step(ck)),
                }
                print(f"paddle_tpu.launch: resuming from {ck}")
        for local in range(args.nproc_per_node):
            rank = base + local
            extra = {"PADDLE_LOCAL_RANK": str(local)}
            extra.update(resume_env)
            if hb_dir is not None:
                extra["PADDLE_ELASTIC_HEARTBEAT_DIR"] = hb_dir
                extra["PADDLE_ELASTIC_HEARTBEAT_RANK"] = str(local)
            if args.nproc_per_node > 1:
                # CPU-simulated cluster: isolate each proc onto CPU devices
                extra["JAX_PLATFORMS"] = "cpu"
            procs.append(_spawn(rank, world, args, extra))
        try:
            outcome = _run_round(procs, args, manager)
        except KeyboardInterrupt:
            _terminate_all(procs)
            raise
        if outcome == "ok":
            print("paddle_tpu.launch: all workers exited cleanly")
            return 0
        _terminate_all(procs)
        # failure detection → checkpoint-restart (elastic mode)
        if restarts >= args.max_restarts:
            print(f"paddle_tpu.launch: worker {outcome}; restarts "
                  f"exhausted", file=sys.stderr)
            return 1
        restarts += 1
        print(f"paddle_tpu.launch: worker {outcome}; relaunching "
              f"({restarts}/{args.max_restarts}) after "
              f"{args.elastic_timeout}s", file=sys.stderr)
        time.sleep(args.elastic_timeout)


if __name__ == "__main__":
    sys.exit(launch_main())
