from .main import launch_main
import sys

sys.exit(launch_main())
