"""Distributed environment / rendezvous.

Reference role: ``init_parallel_env`` + TCPStore + ``PADDLE_TRAINER_*`` env
bootstrap (SURVEY.md §3.3, UNVERIFIED paths). TPU-native: the control plane
is ``jax.distributed`` (gRPC coordination service); the data plane is XLA
collectives over ICI/DCN — there is no ProcessGroup object to create per
communicator, only the global mesh. Rank/world-size here mean *process*
(host) coordinates; device-level parallelism lives in the Mesh."""

from __future__ import annotations

import os

import jax

__all__ = ["init_parallel_env", "get_rank", "get_world_size",
           "is_initialized", "ParallelEnv", "parallel_device_count"]

_initialized = False


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def init_parallel_env():
    """Multi-host init: connect to the coordination service when the
    launcher provided endpoints (PADDLE_TRAINER_* / PADDLE_TPU_* env)."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER",
                           os.environ.get("MASTER_ADDR"))
    nranks = _env_int("PADDLE_TRAINERS_NUM",
                      _env_int("PADDLE_NNODES", 1))
    rank = _env_int("PADDLE_TRAINER_ID", _env_int("PADDLE_RANK", 0))
    if coord and nranks > 1:
        # must NOT probe jax.process_count() here: it would initialize
        # the XLA backend, after which jax.distributed.initialize
        # refuses to run. Ask the distributed client state directly.
        from jax._src import distributed as _jdist
        already = getattr(_jdist.global_state, "client", None) is not None
        if not already:
            port = os.environ.get("MASTER_PORT", "8476")
            addr = coord if ":" in coord else f"{coord}:{port}"
            jax.distributed.initialize(coordinator_address=addr,
                                       num_processes=nranks,
                                       process_id=rank)
    _initialized = True
    return ParallelEnv()


def is_initialized() -> bool:
    return _initialized


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    return jax.process_count()


def parallel_device_count() -> int:
    return jax.device_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return jax.process_count()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank
