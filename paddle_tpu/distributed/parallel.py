"""``paddle.DataParallel`` — dygraph DP wrapper
(python/paddle/parallel/ + EagerReducer parity, UNVERIFIED).

Reference: bucketed overlapped allreduce via EagerReducer (SURVEY.md §3.2).
TPU-native: data parallelism is batch-sharding over the 'data' mesh axis;
gradient reduction is a GSPMD-inserted psum inside the compiled train step —
no reducer object needed. This wrapper keeps the API (``no_sync``,
``scale_loss``) and, when a mesh exists, places parameters replicated over
the data axis so compiled steps behave identically to the reference."""

from __future__ import annotations

import contextlib

from ..framework.core import Tensor
from ..nn.layer.layers import Layer

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        # grad sync happens in the compiled step on TPU; nothing to defer
        yield

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
