"""Distributed sharded checkpoint — ``dist.save_state_dict`` /
``load_state_dict`` parity (UNVERIFIED paths
python/paddle/distributed/checkpoint/save_state_dict.py).

Design (SURVEY.md §5 checkpoint tier 3): each process writes the shards it
owns (addressable shards of each jax.Array) as .npy files plus a metadata
json recording global shape + offsets; load reads whatever shards are
needed and reassembles/re-shards for the target mesh — reshard-on-load
across different parallelism comes free because we reassemble the global
array then device_put with the new sharding."""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _flat(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out



def _save_np(path, arr):
    """np.save with non-native dtypes (bfloat16, fp8) stored as byte-width
    integer views — numpy's npy format cannot round-trip ml_dtypes."""
    arr = np.asarray(arr)
    if arr.dtype.kind == "V" or str(arr.dtype) in (
            "bfloat16", "float8_e4m3fn", "float8_e5m2"):
        view = arr.view(np.uint16 if arr.dtype.itemsize == 2 else np.uint8)
        np.save(path, view)
    else:
        np.save(path, arr)


def _load_np(path, dtype_str):
    data = np.load(path)
    if dtype_str in ("bfloat16", "float8_e4m3fn", "float8_e5m2"):
        import ml_dtypes
        data = data.view(np.dtype(getattr(ml_dtypes, dtype_str)))
    return data


_async_threads = []


def wait_async_save():
    """Join all outstanding async checkpoint writers (called by tests and
    before teardown; paddle's async save exposes the same barrier)."""
    while _async_threads:
        _async_threads.pop().join()


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    """Each rank writes the shards it owns + a metadata json (global shape
    and per-shard offsets). async_save=True snapshots arrays to host, then
    writes in a background thread (the PaddleNLP unified-checkpoint async
    pattern)."""
    if async_save:
        flat = _flat(state_dict)
        host = {}
        for name, t in flat.items():
            if isinstance(t, Tensor):
                arr = t._data
                if isinstance(arr, jax.Array) and \
                        len(arr.sharding.device_set) > 1:
                    shards = [(s.index, np.asarray(s.data))
                              for s in arr.addressable_shards]
                    host[name] = ("sharded", tuple(arr.shape),
                                  str(arr.dtype), shards)
                else:
                    host[name] = ("full", tuple(arr.shape),
                                  str(arr.dtype), np.asarray(arr))
            else:
                host[name] = ("value", None, None, t)
        import threading
        th = threading.Thread(
            target=_write_snapshot, args=(host, path), daemon=False)
        th.start()
        _async_threads.append(th)
        return
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    meta = {}
    flat = _flat(state_dict)
    for name, t in flat.items():
        if not isinstance(t, Tensor):
            meta[name] = {"kind": "value", "value": t}
            continue
        arr = t._data
        shards = []
        safe = name.replace("/", "_")
        if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
            written = set()
            for i, shard in enumerate(arr.addressable_shards):
                idx = shard.index
                offset = tuple(
                    (0 if s.start is None else s.start) for s in idx)
                if offset in written:
                    continue  # replicated copy
                written.add(offset)
                fname = f"{safe}.r{rank}.s{i}.npy"
                _save_np(os.path.join(path, fname),
                         np.asarray(shard.data))
                shards.append({"offset": offset,
                               "local_shape": list(shard.data.shape),
                               "file": fname})
        else:
            fname = f"{safe}.r{rank}.s0.npy"
            _save_np(os.path.join(path, fname), np.asarray(arr))
            shards.append({"offset": [0] * arr.ndim,
                           "local_shape": list(arr.shape),
                           "file": fname})
        meta[name] = {"kind": "tensor",
                      "global_shape": list(arr.shape),
                      "dtype": str(arr.dtype),
                      "shards": shards}
    with open(os.path.join(path, f"meta.{rank}.json"), "w") as f:
        json.dump(meta, f)


def _write_snapshot(host, path):
    """Background writer for async_save: host holds already-snapshotted
    numpy data, so device arrays are not touched off-thread."""
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    meta = {}
    for name, (kind, shape, dtype, payload) in host.items():
        safe = name.replace("/", "_")
        if kind == "value":
            meta[name] = {"kind": "value", "value": payload}
            continue
        shards = []
        if kind == "sharded":
            written = set()
            for i, (idx, data) in enumerate(payload):
                offset = tuple(
                    (0 if s.start is None else s.start) for s in idx)
                if offset in written:
                    continue
                written.add(offset)
                fname = f"{safe}.r{rank}.s{i}.npy"
                _save_np(os.path.join(path, fname), data)
                shards.append({"offset": offset,
                               "local_shape": list(data.shape),
                               "file": fname})
        else:
            fname = f"{safe}.r{rank}.s0.npy"
            _save_np(os.path.join(path, fname), payload)
            shards.append({"offset": [0] * len(shape),
                           "local_shape": list(shape), "file": fname})
        meta[name] = {"kind": "tensor", "global_shape": list(shape),
                      "dtype": dtype, "shards": shards}
    with open(os.path.join(path, f"meta.{rank}.json"), "w") as f:
        json.dump(meta, f)


def _assemble(entry, path):
    shape = tuple(entry["global_shape"])
    dtype = entry["dtype"]
    out = np.zeros(shape, dtype=np.dtype(dtype))
    for sh in entry["shards"]:
        data = _load_np(os.path.join(path, sh["file"]), dtype)
        idx = tuple(slice(o, o + l) for o, l in
                    zip(sh["offset"], sh["local_shape"]))
        out[idx] = data
    return jnp.asarray(out)


def load_state_dict(state_dict, path, process_group=None,
                    unique_id=None, offload=False):
    """In-place load into `state_dict`'s tensors, resharding to each
    target tensor's current sharding."""
    metas = {}
    for fn in sorted(os.listdir(path)):
        if fn.startswith("meta.") and fn.endswith(".json"):
            with open(os.path.join(path, fn)) as f:
                metas.update(json.load(f))
    flat = _flat(state_dict)
    for name, t in flat.items():
        entry = metas.get(name)
        if entry is None:
            continue
        if entry["kind"] == "value":
            continue
        arr = _assemble(entry, path)
        if isinstance(t, Tensor):
            if isinstance(t._data, jax.Array) and \
                    len(t._data.sharding.device_set) > 1:
                # sharded target: reshard the assembled global array onto
                # the target's (possibly different-mesh) sharding
                arr = jax.device_put(arr.astype(t.dtype), t._data.sharding)
            else:
                # single-device target: keep the array uncommitted so it
                # composes with mesh-sharded arrays in eager ops
                arr = arr.astype(t.dtype)
            t.set_data(arr)
    return state_dict
