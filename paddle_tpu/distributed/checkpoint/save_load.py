"""Distributed sharded checkpoint — ``dist.save_state_dict`` /
``load_state_dict`` parity (UNVERIFIED paths
python/paddle/distributed/checkpoint/save_state_dict.py).

Sharding design (SURVEY.md §5 checkpoint tier 3): each process writes
the shards it owns (addressable shards of each jax.Array) as .npy
files plus a metadata json recording global shape + offsets; load
reads whatever shards are needed and reassembles/re-shards for the
target mesh — reshard-on-load across different parallelism comes free
because we reassemble the global array then device_put with the new
sharding.

Crash-safety design (atomic commit protocol): a preempted worker mid-
save must never leave a directory that load will silently partially
read. Every save therefore:

1. writes into a ``<path>.tmp-<uid>`` staging directory, every file
   through :func:`_atomic_write` (stage-to-``.part`` + fsync + size
   check + rename — enforced by tools/check_atomic_writes.py);
2. records a SHA-256 per shard file in the per-rank metadata json;
3. barriers on all ranks' metadata landing in the staging dir
   (shared-filesystem rendezvous — the same channel the shards use).
   Multi-process saves share one deterministic staging dir, so a
   retry after a crash could otherwise satisfy the barrier with a
   *previous* attempt's leftover files; the coordinator therefore
   wipes the stale staging dir and stamps a fresh ``ATTEMPT`` token
   that every rank must echo in its ``ack.<rank>`` before the barrier
   counts it — stale data can never be committed (worst case the
   barrier times out and the save fails uncommitted, the safe
   outcome);
4. has the coordinator rank write a ``COMMITTED`` sentinel (which
   checksums the metadata files themselves) and atomically rename the
   staging dir to the final path.

The rename is the commit point: a crash at ANY earlier instant leaves
only a ``.tmp-`` dir that :func:`load_state_dict` refuses and
``latest_valid_checkpoint`` skips. Load verifies the sentinel, the
metadata checksums, and each shard's SHA-256 before a single byte
reaches a parameter — a checkpoint either loads bit-exactly or raises
:class:`CheckpointCorruptError`. Retention (``keep_last_n``)
garbage-collects superseded committed steps and stale staging dirs
after each successful commit. Validation/discovery/retention live in
the jax-free sibling module :mod:`.validation`.
"""

from __future__ import annotations

import io
import json
import os
import shutil
import threading
import time
import uuid

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from ...profiler import flight_recorder as _frec
from ...profiler import metrics as _pmetrics
from ...utils.retry import retry_call
from .validation import (
    COMMITTED_SENTINEL, CheckpointCorruptError,
    CheckpointNotCommittedError, _active_stages, _read_file,
    _read_metas, _sha256, gc_checkpoints, is_committed,
    latest_valid_checkpoint, validate_checkpoint)

__all__ = [
    "save_state_dict", "load_state_dict", "wait_async_save",
    "latest_valid_checkpoint", "validate_checkpoint", "is_committed",
    "gc_checkpoints", "load_values", "read_state_dict",
    "CheckpointCorruptError", "CheckpointNotCommittedError",
    "COMMITTED_SENTINEL",
]

_FORMAT_VERSION = 1

#: multi-rank attempt token (see module docstring, step 3)
ATTEMPT_FILE = "ATTEMPT"

_pmetrics.declare("elastic/reshard_tensors", "gauge",
                  "tensors laid out for a different mesh during a "
                  "checkpoint load")
_pmetrics.declare("elastic/reshard_ms", "gauge",
                  "wall time of the reshard-on-load pass")


def _flat(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out


def _unflatten(flatmap):
    out = {}
    for k, v in flatmap.items():
        parts = k.split(".")
        cur = out
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return out


def _fsync_dir(path):
    """Best-effort directory fsync so the commit rename survives power
    loss, not just process death (no-op where unsupported)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path, data):
    """THE write primitive for checkpoint files: serialize fully in
    memory first (``data`` is bytes), stage to ``<path>.part``, flush +
    fsync, verify the on-disk size, then atomically rename into place.
    A short write (torn or silently truncated) either raises here or —
    if the kernel lies — mismatches the returned SHA-256 at load.
    Transient I/O errors (ENOSPC freed by GC, EIO blips) are retried
    with bounded backoff. Returns the SHA-256 of ``data``."""
    part = path + ".part"

    def _write():
        with open(part, "wb") as f:  # atomic-ok: the helper itself
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        size = os.stat(part).st_size
        if size != len(data):
            import errno as _e
            raise OSError(_e.EIO,
                          f"short write: {size} != {len(data)}", part)
        os.replace(part, path)

    retry_call(_write)
    return _sha256(data)


def _np_bytes(arr):
    """npy-serialize to bytes; non-native dtypes (bfloat16, fp8) are
    stored as byte-width integer views — numpy's npy format cannot
    round-trip ml_dtypes. The read-side inverse is
    :func:`.reshard._load_shard` (the one shard reader)."""
    from .metadata import NONNATIVE_DTYPES
    arr = np.asarray(arr)
    if arr.dtype.kind == "V" or str(arr.dtype) in NONNATIVE_DTYPES:
        arr = arr.view(np.uint16 if arr.dtype.itemsize == 2 else np.uint8)
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


# --------------------------------------------------------------------------
# save: snapshot -> staged write -> barrier -> commit
# --------------------------------------------------------------------------

_async_threads = []
_async_errors = []


def _raise_pending_async_error():
    if _async_errors:
        err = _async_errors[0]
        _async_errors.clear()
        raise err


def wait_async_save():
    """Join all outstanding async checkpoint writers and re-raise the
    first failure any of them hit — async saves must not fail
    silently. (If the caller never waits, the error surfaces on the
    next ``save_state_dict`` call instead.)"""
    while _async_threads:
        _async_threads.pop().join()
    _raise_pending_async_error()


def _snapshot(state_dict):
    """Snapshot device arrays to host numpy (shared by sync and async
    save, so the writer never touches device state). Each tensor also
    records its placement descriptor (saving mesh + partition spec) —
    sharding specs are data, and a resized fleet reshards from them at
    load."""
    from .metadata import placement_of
    host = {}
    for name, t in _flat(state_dict).items():
        if not isinstance(t, Tensor):
            host[name] = ("value", None, None, t, None)
            continue
        arr = t._data
        placement = placement_of(arr)
        if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
            shards = [(s.index, np.asarray(s.data))
                      for s in arr.addressable_shards]
            host[name] = ("sharded", tuple(arr.shape), str(arr.dtype),
                          shards, placement)
        else:
            host[name] = ("full", tuple(arr.shape), str(arr.dtype),
                          np.asarray(arr), placement)
    return host


def _barrier_timeout():
    return float(os.environ.get("PADDLE_CKPT_BARRIER_TIMEOUT", "300"))


def _wait_for_attempt(stage, timeout):
    """Non-coordinator entry: wait for the coordinator's ATTEMPT token
    (which also guarantees any stale staging dir was already wiped —
    modulo the double-crash race the ack echo closes)."""
    path = os.path.join(stage, ATTEMPT_FILE)
    deadline = time.time() + timeout
    while True:
        try:
            return _read_file(path).decode()
        except OSError:
            pass
        if time.time() > deadline:
            raise RuntimeError(
                f"timed out after {timeout}s waiting for the "
                f"coordinator's {ATTEMPT_FILE} token in {stage} — the "
                f"coordinator likely died before staging began")
        time.sleep(0.05)


def _barrier_on_acks(stage, world, attempt, timeout):
    """Commit barrier: the coordinator waits until every rank's ack —
    echoing THIS attempt's token, so a previous crashed attempt's
    leftovers can never satisfy it — has landed in the staging dir.
    A dead peer means the barrier times out and the checkpoint stays
    uncommitted — exactly the safe outcome."""
    deadline = time.time() + timeout
    while True:
        missing = []
        for r in range(world):
            try:
                ok = _read_file(os.path.join(
                    stage, f"ack.{r}")).decode() == attempt
            except OSError:
                ok = False
            if not ok:
                missing.append(r)
        if not missing:
            return
        if time.time() > deadline:
            raise RuntimeError(
                f"checkpoint commit barrier timed out after {timeout}s "
                f"waiting for ranks {missing} to acknowledge attempt "
                f"{attempt}; a peer rank likely died mid-save — "
                f"staging dir {stage} left uncommitted")
        time.sleep(0.05)


def _commit_rename(stage, final):
    """Atomically promote the staging dir to the final path. An
    existing non-empty final checkpoint is moved aside to
    ``<final>.old`` first and deleted only after the rename lands; if
    a crash hits between the two renames, the ``.old`` backup is still
    a committed checkpoint that ``latest_valid_checkpoint`` considers,
    so an overwrite can never lose the newest committed state."""
    backup = final + ".old"

    def _rename():
        if os.path.isdir(final):
            if os.listdir(final):
                shutil.rmtree(backup, ignore_errors=True)
                os.rename(final, backup)
            else:
                os.rmdir(final)
        os.rename(stage, final)

    retry_call(_rename)
    shutil.rmtree(backup, ignore_errors=True)


def _write_rank_files(host, stage, rank):
    """Write this rank's shards + metadata into the staging dir;
    returns the metadata file's path."""
    meta = {}
    for name, (kind, shape, dtype, payload, placement) in host.items():
        safe = name.replace("/", "_")
        if kind == "value":
            meta[name] = {"kind": "value", "value": payload}
            continue
        shards = []
        if kind == "sharded":
            written = set()
            for i, (idx, data) in enumerate(payload):
                offset = tuple(
                    (0 if s.start is None else s.start) for s in idx)
                if offset in written:
                    continue  # replicated copy
                written.add(offset)
                fname = f"{safe}.r{rank}.s{i}.npy"
                blob = _np_bytes(data)
                sha = _atomic_write(os.path.join(stage, fname), blob)
                shards.append({"offset": list(offset),
                               "local_shape": list(data.shape),
                               "file": fname, "sha256": sha,
                               "nbytes": len(blob)})
        else:
            fname = f"{safe}.r{rank}.s0.npy"
            blob = _np_bytes(payload)
            sha = _atomic_write(os.path.join(stage, fname), blob)
            shards.append({"offset": [0] * len(shape),
                           "local_shape": list(shape),
                           "file": fname, "sha256": sha,
                           "nbytes": len(blob)})
        meta[name] = {"kind": "tensor", "global_shape": list(shape),
                      "dtype": dtype, "shards": shards}
        if placement is not None:
            meta[name]["placement"] = placement
    mpath = os.path.join(stage, f"meta.{rank}.json")
    _atomic_write(mpath, json.dumps(meta).encode())
    return mpath


def _write_checkpoint(host, path, coordinator_rank, uid, keep_last_n,
                      barrier_timeout=None):
    final = os.path.normpath(path)
    stage = f"{final}.tmp-{uid}"
    rank = jax.process_index()
    world = jax.process_count()
    timeout = _barrier_timeout() if barrier_timeout is None \
        else float(barrier_timeout)
    _active_stages.add(stage)
    # flight-recorder breadcrumbs: a save killed mid-protocol leaves
    # the phase it died in inside the crash bundle
    _frec.record_event("checkpoint_phase", phase="stage", path=final,
                       rank=rank)
    try:
        if world <= 1:
            # single process: uid is fresh/random, no stale-staging or
            # rendezvous concerns
            os.makedirs(stage, exist_ok=True)
            _write_rank_files(host, stage, rank)
        elif rank == coordinator_rank:
            # the shared staging dir may hold a crashed attempt's
            # leftovers whose metadata would satisfy the barrier and
            # commit mixed old/new rank data — wipe it and stamp a
            # fresh token every rank must echo. (A stale shard file
            # surviving the wipe is harmless: load only reads files
            # referenced by the fresh metadata.)
            if os.path.isdir(stage):
                shutil.rmtree(stage, ignore_errors=True)
            os.makedirs(stage, exist_ok=True)
            attempt = uuid.uuid4().hex
            _atomic_write(os.path.join(stage, ATTEMPT_FILE),
                          attempt.encode())
            _write_rank_files(host, stage, rank)
            _atomic_write(os.path.join(stage, f"ack.{rank}"),
                          attempt.encode())
        else:
            # re-stage if the coordinator wiped the dir under us (we
            # entered before its cleanup): a mid-write ENOENT or the
            # token changing is the signal; the coordinator wipes at
            # most once per save, so one re-stage normally suffices
            for restage in range(3):
                attempt = _wait_for_attempt(stage, timeout)
                try:
                    _write_rank_files(host, stage, rank)
                    _atomic_write(os.path.join(stage, f"ack.{rank}"),
                                  attempt.encode())
                    if _read_file(os.path.join(
                            stage, ATTEMPT_FILE)).decode() == attempt:
                        break
                except OSError:
                    if restage == 2:
                        raise
            return final
        if world > 1:
            _frec.record_event("checkpoint_phase", phase="barrier",
                               path=final, rank=rank)
            _barrier_on_acks(stage, world, attempt, timeout)
        meta_shas = {}
        for r in range(world):
            mname = f"meta.{r}.json"
            meta_shas[mname] = _sha256(
                _read_file(os.path.join(stage, mname)))
        meshes = []
        for (_kind, _shape, _dtype, _payload, placement) in host.values():
            if placement:
                key = [placement["mesh_shape"], placement["mesh_axes"]]
                if key not in meshes:
                    meshes.append(key)
        sentinel = {"format": _FORMAT_VERSION, "world_size": world,
                    "metas": meta_shas,
                    "topology": {"process_count": world,
                                 "device_count": jax.device_count(),
                                 "meshes": meshes}}
        _atomic_write(os.path.join(stage, COMMITTED_SENTINEL),
                      json.dumps(sentinel).encode())
        _fsync_dir(stage)
        _commit_rename(stage, final)
        _frec.record_event("checkpoint_phase", phase="committed",
                           path=final, rank=rank)
    finally:
        _active_stages.discard(stage)
    parent = os.path.dirname(final) or "."
    _fsync_dir(parent)
    # same-step staging leftovers from earlier crashed attempts
    base = os.path.basename(final)
    try:
        for name in os.listdir(parent):
            full = os.path.join(parent, name)
            if name.startswith(base + ".tmp-") \
                    and full not in _active_stages:
                shutil.rmtree(full, ignore_errors=True)
    except OSError:
        pass
    if keep_last_n is not None:
        gc_checkpoints(parent, keep_last_n)
    return final


def _write_async(host, path, coordinator_rank, uid, keep_last_n,
                 barrier_timeout=None):
    try:
        _write_checkpoint(host, path, coordinator_rank, uid, keep_last_n,
                          barrier_timeout=barrier_timeout)
    except BaseException as e:  # noqa: BLE001 — re-raised at the join
        _async_errors.append(e)


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False,
                    keep_last_n=None, barrier_timeout=None):
    """Crash-safe sharded save (module docstring has the full
    protocol). Each rank writes the shards it owns + a checksummed
    metadata json into a staging dir; the coordinator rank barriers on
    all ranks' attempt-stamped acknowledgements, writes the
    ``COMMITTED`` sentinel, and atomically renames staging to
    ``path``.

    ``unique_id`` names the staging attempt; multi-process saves
    without one use a shared deterministic id (all ranks must stage
    into the same dir without communicating). ``async_save=True``
    snapshots arrays to host, then stages+commits in a background
    thread (the PaddleNLP unified-checkpoint async pattern) — failures
    re-raise from ``wait_async_save`` or the next save call.
    ``keep_last_n`` garbage-collects older committed ``step_N``
    siblings (and stale staging dirs) after commit. ``barrier_timeout``
    overrides the commit-barrier timeout for this save only — the
    bounded-time emergency-checkpoint path (a preempted worker has a
    grace window, not 300 s)."""
    _raise_pending_async_error()
    host = _snapshot(state_dict)
    if unique_id is not None:
        uid = str(unique_id)
    elif jax.process_count() > 1:
        uid = "shared"
    else:
        uid = uuid.uuid4().hex[:8]
    if async_save:
        th = threading.Thread(
            target=_write_async,
            args=(host, path, coordinator_rank, uid, keep_last_n,
                  barrier_timeout),
            daemon=False)
        th.start()
        _async_threads.append(th)
        return
    _write_checkpoint(host, path, coordinator_rank, uid, keep_last_n,
                      barrier_timeout=barrier_timeout)


# --------------------------------------------------------------------------
# load: validate -> assemble -> reshard
# --------------------------------------------------------------------------

def _assemble(entry, path, name, validate=True):
    """Full global tensor as a jnp array — the whole-box case of the
    slice-exact reshard assembler, so checksum verification, missing-
    shard detection, and coverage refusal live in ONE place
    (:func:`.reshard.assemble_slice`)."""
    from .reshard import assemble_slice
    shape = tuple(entry["global_shape"])
    try:
        out = assemble_slice(entry, path, (0,) * len(shape), shape,
                             validate=validate)
    except CheckpointCorruptError as e:
        raise CheckpointCorruptError(f"tensor {name}: {e}")
    return jnp.asarray(out)


def load_state_dict(state_dict, path, process_group=None,
                    unique_id=None, offload=False, validate=True):
    """In-place load into ``state_dict``'s tensors, resharding to each
    target tensor's current sharding. A sharded target goes through
    the slice-exact reshard path (:mod:`.reshard`): only the shards
    overlapping this process's addressable devices are read, so a
    cross-mesh resume (dp/mp resized in either direction) never
    materializes the global tensor and works when not every device is
    addressable. With ``validate=True`` (default) the checkpoint must
    be committed and every byte read is verified against its recorded
    SHA-256: the result is bit-exact or an exception — never a silent
    partial load. ``validate=False`` skips both checks for legacy
    (pre-sentinel) checkpoint dirs."""
    if validate:
        validate_checkpoint(path)
    metas = _read_metas(path)
    flat = _flat(state_dict)
    n_resharded = 0
    t0 = time.perf_counter()
    for name, t in flat.items():
        entry = metas.get(name)
        if entry is None:
            continue
        if entry["kind"] == "value":
            continue
        if isinstance(t, Tensor):
            if isinstance(t._data, jax.Array) and \
                    len(t._data.sharding.device_set) > 1:
                # sharded target: assemble exactly the slices the
                # loading mesh's addressable devices need, directly in
                # the target's (possibly different-mesh) sharding
                from .reshard import reshard_to_sharding
                arr = reshard_to_sharding(
                    entry, path, t._data.sharding,
                    cast_dtype=t._data.dtype, validate=validate)
                n_resharded += 1
            else:
                # single-device target: keep the array uncommitted so it
                # composes with mesh-sharded arrays in eager ops
                arr = _assemble(entry, path, name,
                                validate=validate).astype(t.dtype)
            t.set_data(arr)
    if n_resharded:
        # elastic observability: a cross-mesh resume's reshard cost
        # shows up as a gauge, not a mystery gap in resume time
        reg = _pmetrics.get_registry()
        reg.gauge("elastic/reshard_tensors").set(n_resharded)
        reg.gauge("elastic/reshard_ms").set(
            round((time.perf_counter() - t0) * 1e3, 3))
    return state_dict


def load_values(path, validate=True):
    """The non-tensor entries of a checkpoint (step counters, epoch,
    LR-scheduler scalars) as a nested dict — ``load_state_dict`` only
    fills tensors in place; this returns the rest."""
    if validate:
        validate_checkpoint(path)
    vals = {k: e["value"] for k, e in _read_metas(path).items()
            if e.get("kind") == "value"}
    return _unflatten(vals)


def read_state_dict(path, prefix=None, validate=True):
    """Assemble a checkpoint (or the subtree under ``prefix``) into a
    dict of numpy arrays + values, without needing a target
    state_dict — the resume path for lazily-created state (optimizer
    slots that do not exist yet on a fresh process). Keys are the
    FLAT dotted names (prefix stripped): leaf names may themselves
    contain dots (parameter names), so re-nesting them is ambiguous
    and left to the caller."""
    if validate:
        validate_checkpoint(path)
    metas = _read_metas(path)
    out = {}
    pre = None if prefix is None else prefix + "."
    for name, entry in metas.items():
        if pre is not None:
            if not name.startswith(pre):
                continue
            key = name[len(pre):]
        else:
            key = name
        if entry.get("kind") == "value":
            out[key] = entry["value"]
        else:
            out[key] = np.asarray(
                _assemble(entry, path, name, validate=validate))
    return out
