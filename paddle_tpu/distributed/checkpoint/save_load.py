"""Distributed sharded checkpoint — ``dist.save_state_dict`` /
``load_state_dict`` parity (UNVERIFIED paths
python/paddle/distributed/checkpoint/save_state_dict.py).

Design (SURVEY.md §5 checkpoint tier 3): each process writes the shards it
owns (addressable shards of each jax.Array) as .npy files plus a metadata
json recording global shape + offsets; load reads whatever shards are
needed and reassembles/re-shards for the target mesh — reshard-on-load
across different parallelism comes free because we reassemble the global
array then device_put with the new sharding."""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _flat(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    rank = jax.process_index()
    meta = {}
    flat = _flat(state_dict)
    for name, t in flat.items():
        if not isinstance(t, Tensor):
            meta[name] = {"kind": "value", "value": t}
            continue
        arr = t._data
        shards = []
        safe = name.replace("/", "_")
        if isinstance(arr, jax.Array) and len(arr.sharding.device_set) > 1:
            written = set()
            for i, shard in enumerate(arr.addressable_shards):
                idx = shard.index
                offset = tuple(
                    (0 if s.start is None else s.start) for s in idx)
                if offset in written:
                    continue  # replicated copy
                written.add(offset)
                fname = f"{safe}.r{rank}.s{i}.npy"
                np.save(os.path.join(path, fname),
                        np.asarray(shard.data))
                shards.append({"offset": offset,
                               "local_shape": list(shard.data.shape),
                               "file": fname})
        else:
            fname = f"{safe}.r{rank}.s0.npy"
            np.save(os.path.join(path, fname), np.asarray(arr))
            shards.append({"offset": [0] * arr.ndim,
                           "local_shape": list(arr.shape),
                           "file": fname})
        meta[name] = {"kind": "tensor",
                      "global_shape": list(arr.shape),
                      "dtype": str(arr.dtype),
                      "shards": shards}
    if rank == coordinator_rank:
        with open(os.path.join(path, f"meta.{rank}.json"), "w") as f:
            json.dump(meta, f)
    else:
        with open(os.path.join(path, f"meta.{rank}.json"), "w") as f:
            json.dump(meta, f)


def _assemble(entry, path):
    shape = tuple(entry["global_shape"])
    dtype = entry["dtype"]
    out = np.zeros(shape, dtype=np.dtype(dtype) if dtype != "bfloat16"
                   else np.float32)
    for sh in entry["shards"]:
        data = np.load(os.path.join(path, sh["file"]))
        if dtype == "bfloat16":
            data = data.astype(np.float32)
        idx = tuple(slice(o, o + l) for o, l in
                    zip(sh["offset"], sh["local_shape"]))
        out[idx] = data
    arr = jnp.asarray(out)
    if dtype == "bfloat16":
        arr = arr.astype(jnp.bfloat16)
    return arr


def load_state_dict(state_dict, path, process_group=None,
                    unique_id=None, offload=False):
    """In-place load into `state_dict`'s tensors, resharding to each
    target tensor's current sharding."""
    metas = {}
    for fn in sorted(os.listdir(path)):
        if fn.startswith("meta.") and fn.endswith(".json"):
            with open(os.path.join(path, fn)) as f:
                metas.update(json.load(f))
    flat = _flat(state_dict)
    for name, t in flat.items():
        entry = metas.get(name)
        if entry is None:
            continue
        if entry["kind"] == "value":
            continue
        arr = _assemble(entry, path)
        if isinstance(t, Tensor):
            if isinstance(t._data, jax.Array) and hasattr(t._data,
                                                          "sharding"):
                arr = jax.device_put(arr.astype(t.dtype), t._data.sharding)
            t.set_data(arr)
    return state_dict
