"""Checkpoint metadata — ``paddle.distributed.checkpoint.metadata`` parity
(UNVERIFIED). Records global shape + per-shard offsets so load can reshard
across a different mesh/parallelism.

Topology-aware extension (elastic fault tolerance): sharding specs are
data, not topology (GSPMD) — a checkpoint that records the *saving*
mesh and each tensor's placement can be re-laid-out onto any mesh at
load. :func:`placement_of` serializes a ``jax`` ``NamedSharding`` into
a plain-JSON placement descriptor that the save path embeds in each
tensor's metadata entry, and :class:`MeshTopology` carries the
checkpoint-level view (process count, device count, meshes seen).
These are advisory for the reshard-on-load path (the loader reshards
to the *target* sharding regardless) and authoritative for tooling
that inspects what topology a checkpoint came from."""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["LocalTensorMetadata", "Metadata", "MeshTopology",
           "placement_of", "NONNATIVE_DTYPES"]

#: dtype names numpy's npy format cannot round-trip natively: stored
#: as byte-width integer views on save, re-viewed through ml_dtypes on
#: load. THE single source for both the writer (save_load._np_bytes)
#: and the reader (reshard._load_shard) — extend here, not in place.
NONNATIVE_DTYPES = ("bfloat16", "float8_e4m3fn", "float8_e5m2")


@dataclass
class LocalTensorMetadata:
    global_shape: tuple
    local_shape: tuple
    global_offset: tuple
    dtype: str
    file_name: str = ""


@dataclass
class Metadata:
    state_dict_metadata: dict = field(default_factory=dict)
    # name -> list[LocalTensorMetadata]
    flat_mapping: dict = field(default_factory=dict)


@dataclass
class MeshTopology:
    """The topology a checkpoint was SAVED under — recorded in the
    ``COMMITTED`` sentinel so launchers/tools can tell whether a resume
    is same-topology or a cross-mesh reshard without reading a single
    shard."""
    process_count: int = 1
    device_count: int = 1
    # distinct (mesh_shape, mesh_axes) pairs seen across tensors
    meshes: list = field(default_factory=list)

    def as_dict(self) -> dict:
        return {"process_count": int(self.process_count),
                "device_count": int(self.device_count),
                "meshes": list(self.meshes)}


def placement_of(arr):
    """Serializable placement descriptor of a ``jax.Array``'s
    ``NamedSharding`` (mesh shape + axis names + partition spec), or
    None when the array carries no named sharding (single-device /
    uncommitted arrays have no cross-mesh story to record).

    The spec is stored as a list where each entry is an axis name, a
    list of axis names (a multi-axis dim), or None (replicated dim) —
    exactly ``PartitionSpec``'s structure, JSON-encodable."""
    try:
        from jax.sharding import NamedSharding
    except ImportError:  # pragma: no cover - jax is a hard dep in-tree
        return None
    sharding = getattr(arr, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None

    def _enc(p):
        if p is None:
            return None
        if isinstance(p, (tuple, list)):
            return [str(x) for x in p]
        return str(p)

    return {"mesh_shape": [int(d) for d in sharding.mesh.devices.shape],
            "mesh_axes": [str(a) for a in sharding.mesh.axis_names],
            "spec": [_enc(p) for p in sharding.spec]}
