"""Checkpoint metadata — ``paddle.distributed.checkpoint.metadata`` parity
(UNVERIFIED). Records global shape + per-shard offsets so load can reshard
across a different mesh/parallelism."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class LocalTensorMetadata:
    global_shape: tuple
    local_shape: tuple
    global_offset: tuple
    dtype: str
    file_name: str = ""


@dataclass
class Metadata:
    state_dict_metadata: dict = field(default_factory=dict)
    # name -> list[LocalTensorMetadata]
    flat_mapping: dict = field(default_factory=dict)
