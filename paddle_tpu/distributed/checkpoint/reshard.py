"""Cross-mesh checkpoint resharding — assemble exactly the slices the
*loading* mesh needs from whatever shards the *saving* mesh wrote.

The save path records each shard's global offset + local shape (and,
topology-aware since the elastic PR, the saving mesh + per-tensor
placements); this module is the load-side inverse. The naive path —
assemble the full global tensor on host, then ``device_put`` it with
the target sharding — breaks down twice in production:

- **memory**: a resize-on-preemption resume materializes every global
  tensor on every host, which for a model sharded precisely because it
  does not fit is the one thing the loader must not do;
- **multi-process**: ``device_put`` of a host-global array onto a
  sharding with non-addressable devices does not work — each process
  may only construct the shards it can address.

So :func:`reshard_to_sharding` walks the target sharding's addressable
devices, computes each device's global index box, reads ONLY the saved
shards overlapping that box (:func:`assemble_slice`), verifies their
recorded SHA-256, and builds the array with
``jax.make_array_from_single_device_arrays`` — the global tensor is
never materialized and non-overlapping shard files are never read.
dp/mp resize works in both directions (save@dp=4 → resume@dp=2 or
dp=8): a coarser target reads several saved shards per device, a finer
one reads a sub-slice of a single shard.

Incomplete coverage (a missing rank's shards — some ranks committed,
others not) is a :class:`CheckpointCorruptError`, never a silent
zero-fill."""

from __future__ import annotations

import io
import os

import numpy as np

from .metadata import NONNATIVE_DTYPES
from .validation import (CheckpointCorruptError, _read_file, _read_metas,
                         _sha256, validate_checkpoint)

__all__ = ["assemble_slice", "reshard_to_sharding",
           "checkpoint_topology", "overlapping_shards"]


def _np_dtype(dtype_str):
    """np dtype for a stored dtype string; ml_dtypes names (bfloat16,
    fp8) resolve through ml_dtypes."""
    try:
        return np.dtype(dtype_str)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, dtype_str))


def _load_shard(path, sh, dtype_str, validate, cache):
    """One shard file as a np array, checksum-verified at most once per
    reshard call (``cache`` maps file -> verified array: many target
    devices typically slice the same source shard)."""
    fname = sh["file"]
    arr = cache.get(fname) if cache is not None else None
    if arr is not None:
        return arr
    try:
        blob = _read_file(os.path.join(path, fname))
    except FileNotFoundError:
        raise CheckpointCorruptError(
            f"{path}/{fname}: shard file missing — a rank's shards "
            f"never landed (partial save) or were deleted; refusing "
            f"the torn checkpoint")
    expect = sh.get("sha256")
    if validate and expect:
        actual = _sha256(blob)
        if actual != expect:
            raise CheckpointCorruptError(
                f"{path}/{fname}: shard checksum mismatch (expected "
                f"sha256 {expect}, got {actual}) — refusing to load "
                f"corrupt data")
    arr = np.load(io.BytesIO(blob))
    if dtype_str in NONNATIVE_DTYPES:
        arr = arr.view(_np_dtype(dtype_str))
    if cache is not None:
        cache[fname] = arr
    return arr


def overlapping_shards(entry, starts, stops):
    """The saved shards intersecting the global box [starts, stops),
    as (shard_meta, src_slices, dst_slices) triples — src indexes the
    shard file's array, dst indexes the assembled output box."""
    out = []
    for sh in entry["shards"]:
        off = sh["offset"]
        loc = sh["local_shape"]
        src, dst = [], []
        empty = False
        for d, (a, b) in enumerate(zip(starts, stops)):
            lo = max(a, off[d])
            hi = min(b, off[d] + loc[d])
            if hi <= lo:
                empty = True
                break
            src.append(slice(lo - off[d], hi - off[d]))
            dst.append(slice(lo - a, hi - a))
        if not empty:
            out.append((sh, tuple(src), tuple(dst)))
    return out


def assemble_slice(entry, path, starts, stops, validate=True, cache=None):
    """Assemble the global box [starts, stops) of one tensor entry from
    the shard files that overlap it — non-overlapping files are never
    opened. Raises :class:`CheckpointCorruptError` if the saved shards
    do not cover the requested box (the some-ranks-committed torn
    shape)."""
    shape = tuple(int(b - a) for a, b in zip(starts, stops))
    out = np.zeros(shape, dtype=_np_dtype(entry["dtype"]))
    covered = 0
    total = int(np.prod(shape)) if shape else 1
    for sh, src, dst in overlapping_shards(entry, starts, stops):
        data = _load_shard(path, sh, entry["dtype"], validate, cache)
        out[dst] = data[src]
        covered += int(np.prod([s.stop - s.start for s in dst])) \
            if dst else 1
    # shards are non-overlapping tiles of the global array (replicated
    # copies dedupe at metadata-merge time), so clipped volumes sum to
    # the box volume exactly when coverage is complete
    if covered < total:
        raise CheckpointCorruptError(
            f"{path}: shards cover only {covered}/{total} elements of "
            f"the requested slice of a {entry['global_shape']} tensor "
            f"— a rank's shards are missing (torn multi-rank save); "
            f"refusing the partial state")
    return out


def _norm_box(idx, shape):
    starts = tuple(0 if s.start is None else int(s.start) for s in idx)
    stops = tuple(shape[d] if s.stop is None else int(s.stop)
                  for d, s in enumerate(idx))
    return starts, stops


def reshard_to_sharding(entry, path, sharding, cast_dtype=None,
                        validate=True):
    """Lay one saved tensor out for ``sharding`` (the LOADING mesh),
    reading only the slices this process's devices need. Returns a
    committed ``jax.Array`` with exactly ``sharding``."""
    import jax
    import jax.numpy as jnp

    shape = tuple(entry["global_shape"])
    cache: dict = {}
    arrays = []
    for dev, idx in sharding.addressable_devices_indices_map(
            shape).items():
        starts, stops = _norm_box(idx, shape)
        sl = assemble_slice(entry, path, starts, stops,
                            validate=validate, cache=cache)
        piece = jnp.asarray(sl)
        if cast_dtype is not None:
            piece = piece.astype(cast_dtype)
        arrays.append(jax.device_put(piece, dev))
    return jax.make_array_from_single_device_arrays(
        shape, sharding, arrays)


def checkpoint_topology(path, validate=True):
    """What topology a checkpoint was saved under: the sentinel's
    ``topology`` block (process/device counts, meshes) plus each
    tensor's recorded placement descriptor. Launchers and tools use
    this to report same-topology vs cross-mesh resumes; the loader
    itself reshards to the target sharding regardless."""
    sentinel = validate_checkpoint(path) if validate else {}
    placements = {}
    for name, entry in _read_metas(path).items():
        if entry.get("kind") == "tensor":
            placements[name] = entry.get("placement")
    return {"world_size": sentinel.get("world_size"),
            "topology": sentinel.get("topology"),
            "placements": placements}
