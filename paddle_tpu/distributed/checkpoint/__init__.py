from .save_load import (
    save_state_dict, load_state_dict, wait_async_save,
    latest_valid_checkpoint, validate_checkpoint, is_committed,
    gc_checkpoints, load_values, read_state_dict,
    CheckpointCorruptError, CheckpointNotCommittedError,
    COMMITTED_SENTINEL)
from .metadata import Metadata, LocalTensorMetadata

__all__ = ["save_state_dict", "load_state_dict", "wait_async_save",
           "latest_valid_checkpoint", "validate_checkpoint",
           "is_committed", "gc_checkpoints", "load_values",
           "read_state_dict", "CheckpointCorruptError",
           "CheckpointNotCommittedError", "COMMITTED_SENTINEL",
           "Metadata", "LocalTensorMetadata"]
