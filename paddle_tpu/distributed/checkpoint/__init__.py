from .save_load import save_state_dict, load_state_dict, wait_async_save
from .metadata import Metadata, LocalTensorMetadata

__all__ = ["save_state_dict", "load_state_dict", "wait_async_save",
           "Metadata", "LocalTensorMetadata"]
