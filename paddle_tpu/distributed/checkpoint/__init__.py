from .save_load import (
    save_state_dict, load_state_dict, wait_async_save,
    latest_valid_checkpoint, validate_checkpoint, is_committed,
    gc_checkpoints, load_values, read_state_dict,
    CheckpointCorruptError, CheckpointNotCommittedError,
    COMMITTED_SENTINEL)
from .validation import shards_intact
from .metadata import Metadata, LocalTensorMetadata, MeshTopology, \
    placement_of
from .reshard import (assemble_slice, reshard_to_sharding,
                      checkpoint_topology, overlapping_shards)

__all__ = ["save_state_dict", "load_state_dict", "wait_async_save",
           "latest_valid_checkpoint", "validate_checkpoint",
           "is_committed", "gc_checkpoints", "load_values",
           "read_state_dict", "CheckpointCorruptError",
           "CheckpointNotCommittedError", "COMMITTED_SENTINEL",
           "Metadata", "LocalTensorMetadata", "MeshTopology",
           "placement_of", "assemble_slice", "reshard_to_sharding",
           "checkpoint_topology", "overlapping_shards", "shards_intact"]
