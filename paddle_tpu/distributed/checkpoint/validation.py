"""Checkpoint validation, discovery and retention — the jax-free half
of the crash-safe checkpoint layer (save_load.py has the writer).

Everything here needs only os/json/hashlib, so launcher-side watchers
(`fleet.elastic`, `distributed.launch`) can validate and discover
checkpoints without touching device state. The protocol contract
being checked: a committed checkpoint carries a ``COMMITTED`` sentinel
recording the SHA-256 of every rank's metadata file, and each metadata
entry records the SHA-256 of every shard file it references.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil

from ...utils.retry import retry_call

__all__ = ["is_committed", "validate_checkpoint",
           "latest_valid_checkpoint", "gc_checkpoints",
           "CheckpointCorruptError", "CheckpointNotCommittedError",
           "COMMITTED_SENTINEL"]

#: sentinel file whose presence (written last, pre-rename) marks a
#: fully-committed checkpoint directory
COMMITTED_SENTINEL = "COMMITTED"

#: staging dirs of saves currently in flight in THIS process (async
#: writers register here) — retention GC must never sweep them, even
#: when a newer step commits first
_active_stages = set()


class CheckpointCorruptError(RuntimeError):
    """The checkpoint exists but fails validation (checksum mismatch,
    missing metadata/shard, unreadable sentinel)."""


class CheckpointNotCommittedError(CheckpointCorruptError):
    """The directory never reached the commit point (no ``COMMITTED``
    sentinel): a torn / in-progress save, not a loadable checkpoint."""


def _sha256(data):
    return hashlib.sha256(data).hexdigest()


def _read_file(path):
    def _read():
        with open(path, "rb") as f:
            return f.read()
    return retry_call(_read)


def _read_metas(path):
    metas = {}
    for fn in sorted(os.listdir(path)):
        if fn.startswith("meta.") and fn.endswith(".json"):
            metas.update(json.loads(_read_file(
                os.path.join(path, fn)).decode()))
    return metas


def _step_of(name):
    """Step number encoded in a ``step_N`` basename, else -1."""
    if name.startswith("step_"):
        try:
            return int(name[len("step_"):])
        except ValueError:
            pass
    return -1


def is_committed(path):
    """True iff ``path`` carries the ``COMMITTED`` sentinel."""
    return os.path.isfile(os.path.join(path, COMMITTED_SENTINEL))


def validate_checkpoint(path, deep=False):
    """Raise unless ``path`` is a committed checkpoint whose metadata
    files match the sentinel's checksums; with ``deep=True`` also
    verify every shard file's SHA-256. Returns the parsed sentinel."""
    if not os.path.isdir(path):
        raise CheckpointNotCommittedError(
            f"{path}: not a checkpoint directory")
    spath = os.path.join(path, COMMITTED_SENTINEL)
    if not os.path.isfile(spath):
        raise CheckpointNotCommittedError(
            f"{path}: no {COMMITTED_SENTINEL} sentinel — the save never "
            f"reached its commit point (torn or in-progress checkpoint)")
    try:
        sentinel = json.loads(_read_file(spath).decode())
    except ValueError as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable {COMMITTED_SENTINEL} sentinel: {e}")
    for mname, expect in (sentinel.get("metas") or {}).items():
        mpath = os.path.join(path, mname)
        if not os.path.isfile(mpath):
            raise CheckpointCorruptError(
                f"{path}: committed sentinel names {mname} but the "
                f"file is missing")
        actual = _sha256(_read_file(mpath))
        if expect and actual != expect:
            raise CheckpointCorruptError(
                f"{path}/{mname}: metadata checksum mismatch "
                f"(expected sha256 {expect}, got {actual})")
    if deep:
        for name, entry in _read_metas(path).items():
            if entry.get("kind") != "tensor":
                continue
            for sh in entry["shards"]:
                fpath = os.path.join(path, sh["file"])
                if not os.path.isfile(fpath):
                    raise CheckpointCorruptError(
                        f"{path}: missing shard {sh['file']} of {name}")
                expect = sh.get("sha256")
                if expect:
                    actual = _sha256(_read_file(fpath))
                    if actual != expect:
                        raise CheckpointCorruptError(
                            f"{path}/{sh['file']}: shard checksum "
                            f"mismatch (expected sha256 {expect}, got "
                            f"{actual})")
    return sentinel


def latest_valid_checkpoint(root, deep=False):
    """Newest ``step_N`` subdirectory of ``root`` that is committed and
    passes validation — torn, in-progress, and corrupt checkpoints are
    skipped, so elastic restart / ``Model.fit(resume=True)`` always
    lands on the last *good* step. ``step_N.old`` move-aside backups
    (an overwrite crashed between its two renames) are considered
    after their plain sibling, so that crash window cannot lose the
    newest committed state. Returns None when nothing valid exists."""
    if not os.path.isdir(root):
        return None
    cands = []
    for name in os.listdir(root):
        full = os.path.join(root, name)
        if not os.path.isdir(full):
            continue
        if name.endswith(".old"):
            s = _step_of(name[:-len(".old")])
            rank = 0  # backup: tried after the plain dir of the step
        else:
            s = _step_of(name)
            rank = 1
        if s >= 0:
            cands.append((s, rank, full))
    for _, _, full in sorted(cands, reverse=True):
        try:
            validate_checkpoint(full, deep=deep)
            return full
        except CheckpointCorruptError:
            continue
    return None


def gc_checkpoints(root, keep_last_n, clean_stale=True):
    """Retention: keep the newest ``keep_last_n`` *committed*
    ``step_N`` checkpoints under ``root``; delete older committed
    steps, plus (``clean_stale``) staging dirs, torn step dirs, and
    ``.old`` move-aside backups that are older than the newest
    committed step (never anything newer — that may be a save in
    progress — and never a staging dir this process is still writing).
    Returns the removed paths."""
    if not os.path.isdir(root):
        return []
    committed = []
    for name in os.listdir(root):
        full = os.path.join(root, name)
        s = _step_of(name)
        if s >= 0 and os.path.isdir(full) and is_committed(full):
            committed.append((s, full))
    committed.sort(reverse=True)
    removed = []
    for _, full in committed[max(0, int(keep_last_n)):]:
        shutil.rmtree(full, ignore_errors=True)
        removed.append(full)
    if clean_stale:
        newest = committed[0][0] if committed else -1
        for name in os.listdir(root):
            full = os.path.join(root, name)
            if not os.path.isdir(full) or full in removed:
                continue
            if full in _active_stages:
                continue  # a live writer in this process owns it
            if ".tmp-" in name:
                s = _step_of(name.split(".tmp-")[0])
                if 0 <= s <= newest:
                    shutil.rmtree(full, ignore_errors=True)
                    removed.append(full)
            elif name.endswith(".old"):
                s = _step_of(name[:-len(".old")])
                plain = full[:-len(".old")]
                if 0 <= s <= newest and is_committed(plain):
                    shutil.rmtree(full, ignore_errors=True)
                    removed.append(full)
            else:
                s = _step_of(name)
                if 0 <= s < newest and not is_committed(full):
                    shutil.rmtree(full, ignore_errors=True)
                    removed.append(full)
    return removed
