"""Checkpoint validation, discovery and retention — the jax-free half
of the crash-safe checkpoint layer (save_load.py has the writer).

Everything here needs only os/json/hashlib, so launcher-side watchers
(`fleet.elastic`, `distributed.launch`) can validate and discover
checkpoints without touching device state. The protocol contract
being checked: a committed checkpoint carries a ``COMMITTED`` sentinel
recording the SHA-256 of every rank's metadata file, and each metadata
entry records the SHA-256 of every shard file it references.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil

from ...utils.retry import retry_call

__all__ = ["is_committed", "validate_checkpoint",
           "latest_valid_checkpoint", "gc_checkpoints", "shards_intact",
           "CheckpointCorruptError", "CheckpointNotCommittedError",
           "COMMITTED_SENTINEL"]

#: sentinel file whose presence (written last, pre-rename) marks a
#: fully-committed checkpoint directory
COMMITTED_SENTINEL = "COMMITTED"

#: staging dirs of saves currently in flight in THIS process (async
#: writers register here) — retention GC must never sweep them, even
#: when a newer step commits first
_active_stages = set()


class CheckpointCorruptError(RuntimeError):
    """The checkpoint exists but fails validation (checksum mismatch,
    missing metadata/shard, unreadable sentinel)."""


class CheckpointNotCommittedError(CheckpointCorruptError):
    """The directory never reached the commit point (no ``COMMITTED``
    sentinel): a torn / in-progress save, not a loadable checkpoint."""


def _sha256(data):
    return hashlib.sha256(data).hexdigest()


def _read_file(path):
    def _read():
        with open(path, "rb") as f:
            return f.read()
    return retry_call(_read)


def _read_metas(path):
    """All rank metadata files of a checkpoint, MERGED per tensor.

    A multi-process save writes one ``meta.<rank>.json`` per rank, each
    listing only the shards that rank owned; loading on a different
    world size (the elastic-resume case) must see the union of every
    rank's shards, so tensor entries with the same name merge their
    shard lists. Replicated copies (same global offset written by
    several ranks) dedupe to the first occurrence — coordinator rank 0
    sorts first, so its copy wins."""
    metas = {}
    for fn in sorted(os.listdir(path)):
        if not (fn.startswith("meta.") and fn.endswith(".json")):
            continue
        for name, entry in json.loads(_read_file(
                os.path.join(path, fn)).decode()).items():
            cur = metas.get(name)
            if cur is None:
                metas[name] = entry
            elif cur.get("kind") == "tensor" \
                    and entry.get("kind") == "tensor":
                seen = {tuple(s["offset"]) for s in cur["shards"]}
                for sh in entry.get("shards", []):
                    if tuple(sh["offset"]) not in seen:
                        seen.add(tuple(sh["offset"]))
                        cur["shards"].append(sh)
    return metas


def _step_of(name):
    """Step number encoded in a ``step_N`` basename, else -1."""
    if name.startswith("step_"):
        try:
            return int(name[len("step_"):])
        except ValueError:
            pass
    return -1


def is_committed(path):
    """True iff ``path`` carries the ``COMMITTED`` sentinel."""
    return os.path.isfile(os.path.join(path, COMMITTED_SENTINEL))


def shards_intact(path):
    """Cheap (stat-level, no hashing) check that every shard file the
    metadata references exists with its recorded size. Catches the
    shard-lost-under-a-clean-sentinel rot that shallow validation
    (metadata checksums only) cannot see, at a fraction of ``deep``
    validation's re-hash cost — the discovery/retention middle
    ground."""
    try:
        for entry in _read_metas(path).values():
            if entry.get("kind") != "tensor":
                continue
            for sh in entry["shards"]:
                fpath = os.path.join(path, sh["file"])
                try:
                    size = os.stat(fpath).st_size
                except OSError:
                    return False
                expect = sh.get("nbytes")
                if expect is not None and size != int(expect):
                    return False
    except (OSError, ValueError, KeyError):
        return False
    return True


def validate_checkpoint(path, deep=False):
    """Raise unless ``path`` is a committed checkpoint whose metadata
    files match the sentinel's checksums; with ``deep=True`` also
    verify every shard file's SHA-256. Returns the parsed sentinel."""
    if not os.path.isdir(path):
        raise CheckpointNotCommittedError(
            f"{path}: not a checkpoint directory")
    spath = os.path.join(path, COMMITTED_SENTINEL)
    if not os.path.isfile(spath):
        raise CheckpointNotCommittedError(
            f"{path}: no {COMMITTED_SENTINEL} sentinel — the save never "
            f"reached its commit point (torn or in-progress checkpoint)")
    try:
        sentinel = json.loads(_read_file(spath).decode())
    except ValueError as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable {COMMITTED_SENTINEL} sentinel: {e}")
    for mname, expect in (sentinel.get("metas") or {}).items():
        mpath = os.path.join(path, mname)
        if not os.path.isfile(mpath):
            raise CheckpointCorruptError(
                f"{path}: committed sentinel names {mname} but the "
                f"file is missing")
        actual = _sha256(_read_file(mpath))
        if expect and actual != expect:
            raise CheckpointCorruptError(
                f"{path}/{mname}: metadata checksum mismatch "
                f"(expected sha256 {expect}, got {actual})")
    if deep:
        for name, entry in _read_metas(path).items():
            if entry.get("kind") != "tensor":
                continue
            for sh in entry["shards"]:
                fpath = os.path.join(path, sh["file"])
                if not os.path.isfile(fpath):
                    raise CheckpointCorruptError(
                        f"{path}: missing shard {sh['file']} of {name}")
                expect = sh.get("sha256")
                if expect:
                    actual = _sha256(_read_file(fpath))
                    if actual != expect:
                        raise CheckpointCorruptError(
                            f"{path}/{sh['file']}: shard checksum "
                            f"mismatch (expected sha256 {expect}, got "
                            f"{actual})")
    return sentinel


def latest_valid_checkpoint(root, deep=False):
    """Newest ``step_N`` subdirectory of ``root`` that is committed,
    passes validation, and has every referenced shard file present at
    its recorded size (:func:`shards_intact` — so a shard lost under a
    clean sentinel is skipped without ``deep``'s re-hash cost); torn,
    in-progress, and corrupt checkpoints are skipped, so elastic
    restart / ``Model.fit(resume=True)`` always lands on the last
    *good* step. ``step_N.old`` move-aside backups (an overwrite
    crashed between its two renames) are considered after their plain
    sibling, so that crash window cannot lose the newest committed
    state. Returns None when nothing valid exists."""
    if not os.path.isdir(root):
        return None
    cands = []
    for name in os.listdir(root):
        full = os.path.join(root, name)
        if not os.path.isdir(full):
            continue
        if name.endswith(".old"):
            s = _step_of(name[:-len(".old")])
            rank = 0  # backup: tried after the plain dir of the step
        else:
            s = _step_of(name)
            rank = 1
        if s >= 0:
            cands.append((s, rank, full))
    for _, _, full in sorted(cands, reverse=True):
        try:
            validate_checkpoint(full, deep=deep)
        except CheckpointCorruptError:
            continue
        if shards_intact(full):
            return full
    return None


def gc_checkpoints(root, keep_last_n, clean_stale=True):
    """Retention: keep the newest ``keep_last_n`` *committed*
    ``step_N`` checkpoints under ``root``; delete older committed
    steps, plus (``clean_stale``) staging dirs, torn step dirs, and
    ``.old`` move-aside backups that are older than the newest
    committed step (never anything newer — that may be a save in
    progress — and never a staging dir this process is still writing).

    A sentinel alone is NOT proof a checkpoint is resumable (a shard
    can rot or go missing under a sentinel that still reads clean), so
    retention additionally pins the newest checkpoint that passes
    validation AND has all shard files present at their recorded
    sizes (:func:`shards_intact`): it is never deleted, even when the keep window is
    filled by newer committed-but-corrupt steps and a later save is
    still staging. GC racing an in-flight save must never leave zero
    resumable checkpoints — if that in-flight save dies, the pinned
    step is what the elastic relaunch resumes from.

    Returns the removed paths."""
    if not os.path.isdir(root):
        return []
    committed = []
    for name in os.listdir(root):
        full = os.path.join(root, name)
        s = _step_of(name)
        if s >= 0 and os.path.isdir(full) and is_committed(full):
            committed.append((s, full))
    committed.sort(reverse=True)
    # each candidate is validated at most once per GC pass (the pin
    # loop and the .old sweep would otherwise re-read/re-hash the same
    # metadata — wasted time inside the bounded emergency-save window)
    resumable_memo = {}

    def _resumable(p):
        if p not in resumable_memo:
            try:
                validate_checkpoint(p)
                resumable_memo[p] = shards_intact(p)
            except CheckpointCorruptError:
                resumable_memo[p] = False
        return resumable_memo[p]

    newest_valid = next(
        (full for _, full in committed if _resumable(full)), None)
    removed = []
    for _, full in committed[max(0, int(keep_last_n)):]:
        if full == newest_valid:
            continue  # the last resumable state — never GC it
        shutil.rmtree(full, ignore_errors=True)
        removed.append(full)
    if clean_stale:
        newest = committed[0][0] if committed else -1
        for name in os.listdir(root):
            full = os.path.join(root, name)
            if not os.path.isdir(full) or full in removed:
                continue
            if full in _active_stages:
                continue  # a live writer in this process owns it
            if ".tmp-" in name:
                s = _step_of(name.split(".tmp-")[0])
                if 0 <= s <= newest:
                    shutil.rmtree(full, ignore_errors=True)
                    removed.append(full)
            elif name.endswith(".old"):
                s = _step_of(name[:-len(".old")])
                plain = full[:-len(".old")]
                # the backup may be the only VALID copy of its step: a
                # sentinel on the plain dir is not enough, it must
                # actually validate (metas AND shard files present)
                # before its backup is swept
                plain_ok = is_committed(plain) and _resumable(plain)
                if 0 <= s <= newest and plain_ok:
                    shutil.rmtree(full, ignore_errors=True)
                    removed.append(full)
            else:
                s = _step_of(name)
                if 0 <= s < newest and not is_committed(full):
                    shutil.rmtree(full, ignore_errors=True)
                    removed.append(full)
    return removed
