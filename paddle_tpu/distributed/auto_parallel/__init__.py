"""``paddle.distributed.auto_parallel`` package path parity (reference:
``python/paddle/distributed/auto_parallel/``, UNVERIFIED — mount
empty). The TPU-native implementation lives in ``distributed.mesh``
(ProcessMesh/placements over jax.sharding + GSPMD) and
``distributed.api_static`` (dist.to_static); this package re-exports
the reference import paths."""

from ..mesh import (Partial, Placement, ProcessMesh, Replicate, Shard,
                    dtensor_from_fn, get_mesh, reshard, set_mesh,
                    shard_layer, shard_op, shard_optimizer, shard_tensor)
from ..auto_parallel_api import Strategy, to_static

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "shard_layer", "shard_op", "shard_optimizer",
           "reshard", "dtensor_from_fn", "get_mesh", "set_mesh",
           "Strategy", "to_static"]
