"""Explicit-schedule pipeline training: true 1F1B and ZB-H1 zero-bubble.

Reference parity: fleet ``pipeline_parallel.py`` schedules "FThenB, 1F1B,
interleaved-1F1B, ZB-H1 zero-bubble" (SURVEY.md §2.2 PP row; reference
mount empty, no file:line cites). The reference runs these schedules as a
host-side loop issuing NCCL p2p sends/recvs between stage *processes*.

TPU-native design — NOT a port. The whole schedule is ONE compiled
program, SPMD over the mesh's 'pipe' axis:

- A *schedule table* is built ahead of time by a greedy lock-step list
  scheduler (``make_schedule``): for every tick t and stage d it records
  which work unit (NOP / F / B / W, microbatch m) that stage executes.
  The table is a static int32 array baked into the compiled program.
- A ``lax.scan`` over ticks executes the table: each tick every device
  banks the activation/gradient that arrived over ICI on the previous
  tick (one ``lax.ppermute`` hop in each direction — the role NCCL p2p
  plays on GPU), then ``lax.switch``-es into its scheduled work unit.
- F saves the stage input x[m]; B *recomputes* the stage forward inside
  ``jax.vjp`` (rematerialization — the TPU-idiomatic trade of FLOPs for
  HBM, so only microbatch *inputs*, not per-layer residuals, stay live).
- ZB-H1 (Qi et al., "Zero Bubble Pipeline Parallelism") splits backward
  into B (input gradient — the inter-stage critical path) and W (weight
  gradient — no consumer until optimizer.step). B is scheduled with
  priority; W fills ticks that 1F1B would leave idle, collapsing the
  drain-phase bubble. Here B computes only dx (vjp of the x-closure) and
  W computes dp (vjp of the p-closure) — each recomputes the stage
  forward, keeping the B tick strictly cheaper than a fused B+W tick
  exactly as the ZB schedule assumes.

Schedules:
- 'fthenb'  — forward wave then backward wave (GPipe); W fused into B.
- '1f1b'    — warmup/steady/cooldown with in-flight cap S-d; W fused.
- 'zb_h1'   — 1F1B-shaped with split B/W; W greedily fills idle ticks.

Constraint (same as ``pipeline.py``): stage_fn is shape/dtype-preserving,
so one activation buffer shape serves every stage.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.jax_compat import shard_map as _shard_map
import numpy as np
from jax import lax

__all__ = ["make_schedule", "pipeline_train_spmd", "run_pipeline_train",
           "NOP", "F", "B", "W"]

NOP, F, B, W = 0, 1, 2, 3


# --------------------------------------------------------------------------
# Schedule construction (static, host-side)
# --------------------------------------------------------------------------

def make_schedule(S, M, kind="1f1b"):
    """Greedy lock-step list scheduler.

    Model: at each tick every stage executes one work unit; a message
    sent at tick t (F's activation to stage d+1, B's gradient to stage
    d-1) is available to its consumer from tick t+1.

    Readiness rules:
      F(m, 0)   : always.
      F(m, d)   : F(m, d-1) finished at some tick <= t-1.
      B(m, S-1) : F(m, S-1) finished at <= t-1 (input x[m] saved; loss
                  vjp recomputes the forward).
      B(m, d)   : B(m, d+1) finished at <= t-1 (gradient arrived).
      W(m, d)   : B(m, d) finished (same stage, earlier tick).

    Policies:
      fthenb: priority F > B, no in-flight cap (GPipe shape).
      1f1b  : priority B > F; in-flight cap (F issued - B done) <= S-d.
      zb_h1 : priority B > F > W; same cap; W fills idle ticks.

    Returns (op_table, mb_table): np.int32 arrays of shape [S, T].
    """
    if kind not in ("fthenb", "1f1b", "zb_h1"):
        raise ValueError(f"unknown pipeline schedule '{kind}'")
    split_w = kind == "zb_h1"
    f_done = [[-1] * M for _ in range(S)]   # tick F(m,d) completed
    b_done = [[-1] * M for _ in range(S)]
    w_done = [[-1] * M for _ in range(S)]
    f_next = [0] * S                        # microbatches issued in order
    b_next = [0] * S
    w_next = [0] * S                        # W issued FIFO too
    ops, mbs = [], []
    t = 0
    total = S * M * (3 if split_w else 2)
    done = 0
    while done < total:
        row_op = [NOP] * S
        row_mb = [0] * S
        for d in range(S):
            cap = S - d
            f_ready = (f_next[d] < M and
                       (d == 0 or f_done[d - 1][f_next[d]] >= 0) and
                       (kind == "fthenb" or
                        f_next[d] - b_next[d] < cap))
            m = b_next[d]
            if d == S - 1:
                b_ready = m < M and f_done[d][m] >= 0
            else:
                b_ready = m < M and b_done[d + 1][m] >= 0
            w_ready = (split_w and w_next[d] < M
                       and b_done[d][w_next[d]] >= 0)
            if kind == "fthenb":
                order = ("F", "B")
            else:
                order = ("B", "F", "W") if split_w else ("B", "F")
            for o in order:
                if o == "F" and f_ready:
                    row_op[d], row_mb[d] = F, f_next[d]
                    break
                if o == "B" and b_ready:
                    row_op[d], row_mb[d] = B, m
                    break
                if o == "W" and w_ready:
                    row_op[d], row_mb[d] = W, w_next[d]
                    break
        # commit the tick (completion recorded after selection so a
        # message sent this tick is consumable only from t+1)
        for d in range(S):
            o, m = row_op[d], row_mb[d]
            if o == F:
                f_done[d][m] = t
                f_next[d] += 1
                done += 1
            elif o == B:
                b_done[d][m] = t
                b_next[d] += 1
                done += 1
            elif o == W:
                w_done[d][m] = t
                w_next[d] += 1
                done += 1
        ops.append(row_op)
        mbs.append(row_mb)
        t += 1
        if t > 8 * (M + S) * (3 if split_w else 2) + 64:
            raise RuntimeError("schedule construction did not converge")
    op_table = np.array(ops, dtype=np.int32).T  # [S, T]
    mb_table = np.array(mbs, dtype=np.int32).T
    return op_table, mb_table


def _buffer_slots(op_table, mb_table, S, M, split_w):
    """Static buffer sizing: the peak number of simultaneously-live
    stage inputs (x) and banked gradients (g) across stages.

    x[m] on stage d is live from its banking tick (activation arrival =
    F(m,d-1)+1; F tick itself on stage 0) until its last use (W(m,d)
    when split, else B(m,d)). g[m] is live from B(m,d+1)+1 until W(m,d)
    / B(m,d). Both are issued and released in microbatch order (FIFO),
    so the live set is a contiguous window and ``slot = m % K`` with K =
    peak window size is collision-free. This is what makes 1F1B/ZB-H1's
    in-flight cap an actual memory bound — K is S-ish, not M.
    """
    f_at = {}
    b_at = {}
    w_at = {}
    T = op_table.shape[1]
    for t in range(T):
        for d in range(S):
            o, m = int(op_table[d, t]), int(mb_table[d, t])
            if o == F:
                f_at[(d, m)] = t
            elif o == B:
                b_at[(d, m)] = t
            elif o == W:
                w_at[(d, m)] = t

    def peak(intervals):
        events = []
        for s, e in intervals:
            events.append((s, 1))
            events.append((e + 1, -1))
        events.sort()
        cur = best = 0
        for _, delta in events:
            cur += delta
            best = max(best, cur)
        return best

    kx = kg = 1
    for d in range(S):
        x_iv = []
        g_iv = []
        for m in range(M):
            start = f_at[(d, m)] if d == 0 else f_at[(d - 1, m)] + 1
            end = w_at[(d, m)] if split_w else b_at[(d, m)]
            x_iv.append((start, end))
            if d < S - 1:
                g_start = b_at[(d + 1, m)] + 1
                g_end = w_at[(d, m)] if split_w else b_at[(d, m)]
                g_iv.append((g_start, g_end))
        kx = max(kx, peak(x_iv))
        if g_iv:
            kg = max(kg, peak(g_iv))
    return kx, kg


# --------------------------------------------------------------------------
# SPMD tick machine
# --------------------------------------------------------------------------

from .pipeline import _vary  # noqa: E402 — shared pcast/pvary shim


def pipeline_train_spmd(stage_fn, loss_fn, stage_params, x_micro,
                        tgt_micro, axis_name, n_stages,
                        schedule="zb_h1", epi_fn=None, epi_params=None,
                        extra_axes=(), expert_axes=()):
    """Run one pipelined train step inside a shard_map region.

    stage_fn(params_one_stage, x) -> y, shape/dtype preserving.
    loss_fn(y, tgt) -> scalar, applied per microbatch on the last stage;
      total loss is the SUM over microbatches (divide by M outside for
      mean semantics).
    stage_params: pytree, local leaves [1, ...] (dim 0 sharded 'pipe').
    x_micro, tgt_micro: [M, ...] replicated over the pipe axis.
    n_stages: static pipe-axis size (the mesh shape).

    Full-model mode (``epi_fn`` given): the last-stage loss becomes
    ``epi_fn(y, tgt, epi_params)`` — the PipelineLayer's epilogue
    (norm/head) + loss, with ``epi_params`` a replicated pytree — and the
    engine additionally returns the gradients an enclosing autograd tape
    needs: d(loss)/d(x_micro) (for the prologue/embedding backward) and
    d(loss)/d(epi_params).

    Returns (loss, dparams, y_micro), or with ``epi_fn``:
    (loss, dparams, y_micro, dx_micro, depi). loss replicated after psum;
    dparams matches stage_params' local structure; y_micro [M, ...]
    last-stage outputs.

    extra_axes — the 5D pp x sep composition: additional manual axes the
    enclosing shard_map binds (the activations arrive sequence-sharded
    over them, stage_fn's ring attention uses them directly, and epi_fn
    is expected to all_gather before the loss so it returns the FULL
    loss on every rank). The sep collectives inside the pipe-varying
    lax.switch/cond branches are safe: the branch index depends only on
    the pipe coordinate, so all sep-peers of a fiber enter each
    collective together. At the end, stage grads are psum'd over the
    extra axes (their token shards are partial sums) while loss/depi —
    identical on every rank after the gather — are psum/size-normalized
    back to invariance.

    expert_axes — the ep x pp composition (MoE under 1F1B/ZB-H1):
    manual axes over which activations stay REPLICATED while some
    stage-param leaves (the expert weight banks) arrive SHARDED.
    MoELayer's manual-region path slices its token shard by axis index,
    runs the all-to-all dispatch on the bound axis, and reassembles the
    full token set with a masked psum — so every inter-tick value
    (activations, gradients, loss) is expert-INVARIANT, and no
    engine-side buffer plumbing changes. The gradient story rides jax's
    typed-vma transpose: cotangents of expert-invariant primals (shared
    params, dx) come back invariant (the pvary transpose inserts the
    psum over 'expert' inside the vjp), while cotangents of the
    expert-sharded bank leaves stay local shards — which is exactly the
    ep-aware reduction: NO engine-side psum over expert_axes at all.
    Per-leaf vma is inherited from the params themselves
    (``zeros_like(p_local)``), so bank-grad accumulators are
    expert-varying and shared-grad accumulators are not.
    """
    S = int(n_stages)
    d = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    op_np, mb_np = make_schedule(S, M, schedule)
    T = op_np.shape[1]
    op_table = jnp.asarray(op_np)
    mb_table = jnp.asarray(mb_np)
    split_w = schedule == "zb_h1"
    # K-slot recycled buffers: peak in-flight count, not M (the memory
    # bound the 1F1B/ZB schedules exist to provide)
    kx, kg = _buffer_slots(op_np, mb_np, S, M, split_w)

    full_model = epi_fn is not None
    epi = epi_params if full_model else ()
    has_epi_params = bool(jax.tree.leaves(epi))

    p_local = jax.tree.map(lambda q: lax.index_in_dim(q, 0, 0, False),
                           stage_params)

    def _zeros_vma_like(q):
        """Zeros with q's vma (+ pipe + x_micro's axes) — bank leaves
        sharded over an expert axis must have expert-varying grad
        accumulators while shared-param leaves stay expert-invariant."""
        from ..framework._vma import pvary_missing
        try:
            inherited = tuple(jax.typeof(q).vma)
        except Exception:
            inherited = ()
        return pvary_missing(jnp.zeros_like(q),
                             inherited + (axis_name,), like=x_micro)
    # Differentiating wrt an UNVARIED value under the device-varying
    # lax.cond(is_last, ...) would make jax insert the pvary-transpose
    # psum INSIDE the last-stage-only branch — a collective that only one
    # device reaches (deadlock). Cast epi params varying up front so
    # their grads stay local; the single psum at the end does the reduce.
    epi_v = jax.tree.map(
        lambda q: _vary(q, axis_name, like=x_micro), epi)

    def apply_stage(p, x):
        return stage_fn(p, x)

    def last_loss(pp, xx, ee, tgt):
        y = apply_stage(pp, xx)
        return epi_fn(y, tgt, ee) if full_model else loss_fn(y, tgt)

    xbuf0 = _vary(jnp.zeros((kx,) + mb_shape, x_micro.dtype), axis_name,
                  like=x_micro)
    ybuf0 = _vary(jnp.zeros_like(x_micro), axis_name, like=x_micro)
    gbuf0 = _vary(jnp.zeros((kg,) + mb_shape, x_micro.dtype), axis_name,
                  like=x_micro)
    # the [M, ...] input-gradient bank exists only in full-model mode —
    # plain callers keep the K-slot memory bound (None = empty pytree)
    dxbuf0 = _vary(jnp.zeros_like(x_micro), axis_name, like=x_micro) \
        if full_model else None
    dp0 = jax.tree.map(
        _zeros_vma_like if (extra_axes or expert_axes)
        else jnp.zeros_like, stage_params)
    # epi_params arrive replicated (P()); the accumulator must be varying
    # over the pipe axis like every other carry buffer
    depi0 = jax.tree.map(
        lambda q: _vary(jnp.zeros_like(q), axis_name, like=x_micro), epi)
    # branch outputs must agree on varying-axis type: every constant a
    # branch can return is pre-cast to varying over the pipe axis
    zeros_mb = _vary(jnp.zeros(mb_shape, x_micro.dtype), axis_name,
                     like=x_micro)
    zero_loss = _vary(jnp.zeros((), jnp.float32), axis_name,
                      like=x_micro)
    # branch-constant shapes follow p_local (the [1, ...]-indexed leaf),
    # inheriting its vma: expert-sharded bank leaves yield expert-varying
    # zeros, shared leaves expert-invariant ones
    zero_dp = jax.tree.map(_zeros_vma_like, p_local)
    zero_depi = jax.tree.map(
        lambda q: _vary(jnp.zeros_like(q), axis_name, like=x_micro), epi)
    fmsg0 = zeros_mb
    bmsg0 = zeros_mb
    loss0 = zero_loss

    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    bwd_perm = [(i + 1, i) for i in range(S - 1)]

    def tick(carry, t):
        xbuf, ybuf, gbuf, dxbuf, dp, depi, loss, fmsg, bmsg = carry
        tm1 = jnp.maximum(t - 1, 0)
        my_op = op_table[d, t]
        my_m = mb_table[d, t]
        # ---- bank arrivals from the previous tick (slot = m % K) ----
        dprev = jnp.clip(d - 1, 0, S - 1)
        prev_was_f = (t > 0) & (d > 0) & (op_table[dprev, tm1] == F)
        # stage 0 banks its own fresh microbatch at its F tick instead
        stage0_f = (d == 0) & (my_op == F)
        slot_f = jnp.where(stage0_f, my_m, mb_table[dprev, tm1]) % kx
        xval = jnp.where(
            stage0_f,
            lax.dynamic_index_in_dim(x_micro, my_m, 0, False), fmsg)
        cur = lax.dynamic_index_in_dim(xbuf, slot_f, 0, False)
        xbuf = lax.dynamic_update_index_in_dim(
            xbuf, jnp.where(prev_was_f | stage0_f, xval, cur), slot_f, 0)
        dnext = jnp.clip(d + 1, 0, S - 1)
        next_was_b = (t > 0) & (d < S - 1) & (op_table[dnext, tm1] == B)
        slot_b = mb_table[dnext, tm1] % kg
        curg = lax.dynamic_index_in_dim(gbuf, slot_b, 0, False)
        gbuf = lax.dynamic_update_index_in_dim(
            gbuf, jnp.where(next_was_b, bmsg, curg), slot_b, 0)

        # ---- this tick's work unit ----
        x = lax.dynamic_index_in_dim(xbuf, my_m % kx, 0, False)
        tgt = lax.dynamic_index_in_dim(tgt_micro, my_m, 0, False)
        is_last = d == S - 1
        is_first = d == 0

        def do_nop(xb, yb, gb, dxb, dp, depi, loss):
            return xb, yb, gb, dxb, dp, depi, loss, zeros_mb, zeros_mb

        def do_f(xb, yb, gb, dxb, dp, depi, loss):
            y = apply_stage(p_local, x)
            cury = lax.dynamic_index_in_dim(yb, my_m, 0, False)
            yb = lax.dynamic_update_index_in_dim(
                yb, jnp.where(is_last, y, cury), my_m, 0)
            return xb, yb, gb, dxb, dp, depi, loss, y, zeros_mb

        def do_b(xb, yb, gb, dxb, dp, depi, loss):
            dy = lax.dynamic_index_in_dim(gb, my_m % kg, 0, False)

            def last_branch(_):
                if split_w:
                    lm, dx = jax.value_and_grad(
                        lambda xx: last_loss(p_local, xx, epi_v, tgt))(x)
                    return lm.astype(jnp.float32), dx, zero_dp, zero_depi
                if has_epi_params:
                    lm, (dpm, dx, depim) = jax.value_and_grad(
                        last_loss, argnums=(0, 1, 2))(p_local, x, epi_v,
                                                      tgt)
                else:
                    lm, (dpm, dx) = jax.value_and_grad(
                        last_loss, argnums=(0, 1))(p_local, x, epi_v, tgt)
                    depim = zero_depi
                return lm.astype(jnp.float32), dx, dpm, depim

            def mid_branch(_):
                if split_w:
                    _, vjp = jax.vjp(
                        lambda xx: apply_stage(p_local, xx), x)
                    (dx,) = vjp(dy)
                    return zero_loss, dx, zero_dp, zero_depi
                _, vjp = jax.vjp(apply_stage, p_local, x)
                dpm, dx = vjp(dy)
                return zero_loss, dx, dpm, zero_depi

            lm, dx, dpm, depim = lax.cond(is_last, last_branch,
                                          mid_branch, None)
            dp = jax.tree.map(lambda a, g: a + g[None], dp, dpm)
            depi = jax.tree.map(jnp.add, depi, depim)
            if full_model:
                # stage 0's input gradient feeds the enclosing tape's
                # prologue backward; other stages ship dx over ICI
                curdx = lax.dynamic_index_in_dim(dxb, my_m, 0, False)
                dxb = lax.dynamic_update_index_in_dim(
                    dxb, jnp.where(is_first, dx, curdx), my_m, 0)
            return xb, yb, gb, dxb, dp, depi, loss + lm, zeros_mb, dx

        def do_w(xb, yb, gb, dxb, dp, depi, loss):
            dy = lax.dynamic_index_in_dim(gb, my_m % kg, 0, False)

            def last_branch(_):
                if has_epi_params:
                    dpm, depim = jax.grad(
                        last_loss, argnums=(0, 2))(p_local, x, epi_v, tgt)
                    return dpm, depim
                dpm = jax.grad(last_loss)(p_local, x, epi_v, tgt)
                return dpm, zero_depi

            def mid_branch(_):
                _, vjp = jax.vjp(lambda pp: apply_stage(pp, x), p_local)
                (dpm,) = vjp(dy)
                return dpm, zero_depi

            dpm, depim = lax.cond(is_last, last_branch, mid_branch, None)
            dp = jax.tree.map(lambda a, g: a + g[None], dp, dpm)
            depi = jax.tree.map(jnp.add, depi, depim)
            return xb, yb, gb, dxb, dp, depi, loss, zeros_mb, zeros_mb

        xbuf, ybuf, gbuf, dxbuf, dp, depi, loss, fout, bout = lax.switch(
            my_op, [do_nop, do_f, do_b, do_w],
            xbuf, ybuf, gbuf, dxbuf, dp, depi, loss)

        fmsg_n = lax.ppermute(fout, axis_name, fwd_perm)
        # ORDER the two per-tick hops: without a data dependency the
        # forward-hop and backward-hop ppermutes are independent, and a
        # runtime with no global collective ordering (XLA:CPU thunks;
        # 16-device virtual meshes) can have half the devices enter one
        # and half the other — a rendezvous deadlock. The barrier ties
        # the backward hop's input to the forward hop's completion, so
        # every device issues them in the same order. On TPU this costs
        # nothing (the transfers still overlap compute; they ride
        # opposite ICI directions).
        bout, _ = lax.optimization_barrier((bout, fmsg_n))
        bmsg_n = lax.ppermute(bout, axis_name, bwd_perm)
        return (xbuf, ybuf, gbuf, dxbuf, dp, depi, loss,
                fmsg_n, bmsg_n), None

    carry0 = (xbuf0, ybuf0, gbuf0, dxbuf0, dp0, depi0, loss0, fmsg0, bmsg0)
    (xbuf, ybuf, gbuf, dxbuf, dp, depi, loss, _, _), _ = lax.scan(
        tick, carry0, jnp.arange(T))
    last_mask = d == S - 1
    loss = lax.psum(jnp.where(last_mask, loss, 0.0), axis_name)
    y_micro = lax.psum(ybuf * last_mask.astype(ybuf.dtype), axis_name)
    for ax in extra_axes:
        n_ax = lax.psum(1, ax)
        # after epi_fn's all_gather the loss is the FULL loss on every
        # sep rank: normalize back to invariance. Stage grads are
        # per-token-shard partial sums: plain psum.
        loss = lax.psum(loss, ax) / n_ax
        dp = jax.tree.map(lambda q: lax.psum(q, ax), dp)
    if not full_model:
        return loss, dp, y_micro
    first_mask = (d == 0).astype(dxbuf.dtype)
    dx_micro = lax.psum(dxbuf * first_mask, axis_name)
    depi = jax.tree.map(lambda q: lax.psum(q, axis_name), depi)
    for ax in extra_axes:
        n_ax = lax.psum(1, ax)
        depi = jax.tree.map(lambda q: lax.psum(q, ax) / n_ax, depi)
    return loss, dp, y_micro, dx_micro, depi


def run_pipeline_train(stage_fn, loss_fn, stacked_params, x_micro,
                       tgt_micro, mesh, axis_name="pipe",
                       schedule="zb_h1", epi_fn=None, epi_params=None,
                       extra_axes=(), x_spec=None, param_specs=None,
                       expert_axes=()):
    """Global-view entry: partial-manual shard_map over the pipe axis.

    stacked_params leaves: [S, ...] sharded on dim 0 over ``axis_name``
    (``param_specs`` overrides per leaf — e.g. P('pipe', 'expert') keeps
    an expert bank's expert dim sharded through the region; the same
    specs shard the returned dparams, which for those leaves are local
    shards, not psum'd — see pipeline_train_spmd's expert_axes note).
    Returns (loss_sum, dparams [S, ...] stacked, y_micro [M, ...]); with
    ``epi_fn`` (full-model mode, see pipeline_train_spmd) additionally
    (..., dx_micro [M, ...], depi)."""
    from jax.sharding import PartitionSpec as P

    S = int(mesh.shape[axis_name])
    pspecs = param_specs if param_specs is not None else \
        jax.tree.map(lambda _: P(axis_name), stacked_params)
    if epi_fn is None:
        if extra_axes or expert_axes or x_spec is not None:
            raise ValueError(
                "extra_axes/expert_axes/x_spec (the pp x sep/ep "
                "compositions) require full-model mode: pass epi_fn")
        f = _shard_map(
            functools.partial(pipeline_train_spmd, stage_fn, loss_fn,
                              axis_name=axis_name, n_stages=S,
                              schedule=schedule),
            mesh=mesh,
            in_specs=(pspecs, P(), P()),
            out_specs=(P(), pspecs, P()),
            axis_names={axis_name},
        )
        return f(stacked_params, x_micro, tgt_micro)
    epi_specs = jax.tree.map(lambda _: P(), epi_params)
    if x_spec is None:
        x_spec = P()

    def wrapped(sp, xm, tm, ep):
        return pipeline_train_spmd(stage_fn, loss_fn, sp, xm, tm,
                                   axis_name=axis_name, n_stages=S,
                                   schedule=schedule, epi_fn=epi_fn,
                                   epi_params=ep, extra_axes=extra_axes,
                                   expert_axes=expert_axes)

    f = _shard_map(
        wrapped,
        mesh=mesh,
        # targets stay replicated (epi_fn gathers hidden states before
        # the loss, so it needs the full label sequence); activations
        # and their gradients ride x_spec over the extra axes
        in_specs=(pspecs, x_spec, P(), epi_specs),
        out_specs=(P(), pspecs, x_spec, x_spec, epi_specs),
        axis_names={axis_name, *extra_axes, *expert_axes},
    )
    return f(stacked_params, x_micro, tgt_micro, epi_params)
