"""``paddle.distributed.communication`` — collective API
(python/paddle/distributed/communication/ parity, UNVERIFIED).

Reference mechanism: eager NCCL collectives through ProcessGroup (SURVEY.md
§2.1). TPU-native mechanism: collectives are *compiled* XLA ops over mesh
axes. This module therefore has two modes:

- **Traced mode** (inside ``shard_map``/``pjit`` over a mesh axis): calls
  lower to ``lax.psum/all_gather/ppermute/all_to_all`` on the group's axis
  name — this is the hot path used by the parallel layers and pipeline
  schedules.
- **Eager mode** (plain dygraph): with one participant they are identity
  ops (matching single-process paddle). In a multi-PROCESS job
  (launcher-spawned ranks / multi-host) eager collectives run
  host-mediated through the jax.distributed coordination service — the
  role Gloo plays in the reference's no-GPU path. Eager multi-DEVICE
  collectives within one process still raise with guidance (data-plane
  comm belongs inside the compiled program on TPU).

Groups carry a mesh-axis name instead of an NCCL communicator."""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.core import Tensor, apply
from .env import get_rank, get_world_size

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "all_reduce",
           "all_gather", "all_gather_object", "reduce_scatter", "alltoall",
           "alltoall_single", "broadcast", "broadcast_object_list", "reduce",
           "scatter", "send", "recv", "isend", "irecv", "barrier", "wait",
           "P2POp", "batch_isend_irecv", "stream", "in_traced_collective"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


@dataclass
class Group:
    id: int = 0
    ranks: list = field(default_factory=list)
    axis_name: str | None = None  # mesh axis this group maps onto

    @property
    def nranks(self):
        if self.axis_name is not None and _axis_bound(self.axis_name):
            return lax.axis_size(self.axis_name)
        return len(self.ranks) if self.ranks else max(get_world_size(), 1)

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if self.ranks else rank


_groups: dict[int, Group] = {}
_next_gid = [1]
_default_group = Group(0, [], None)
_groups[0] = _default_group


def _axis_bound(name: str) -> bool:
    """True when `name` is a mapped axis in the current trace context."""
    if name is None:
        return False
    try:
        lax.axis_size(name)
        return True
    except (NameError, KeyError, Exception):
        return False


def in_traced_collective(group=None) -> bool:
    """Inside a traced manual-collective region for ``group``. With no
    group (or the axis-less default group): inside ANY mapped-axis
    region (shard_map) — per-device values there must not be treated as
    global."""
    g = group or _default_group
    if g.axis_name is not None:
        return _axis_bound(g.axis_name)
    from jax._src import core as _core
    try:
        return bool(_core.nonempty_axis_env())
    except Exception:
        return False


def axis_in_traced_region(name) -> bool:
    """True when the NAMED mesh axis is bound in the current trace — the
    guard TP/SP layers need (a shard_map over 'pipe' must not flip a
    'model'-axis layer into its explicit-collective branch)."""
    return _axis_bound(name)


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    gid = _next_gid[0]
    _next_gid[0] += 1
    g = Group(gid, list(ranks) if ranks else [], axis_name)
    _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    return _groups.get(gid, _default_group)


def _axis(group) -> str | None:
    g = group or _default_group
    return g.axis_name


def _traced_axis_active(group) -> bool:
    """The collective-routing guard: this group carries an axis name AND
    that axis is bound in the current trace. (in_traced_collective with
    no group answers the broader 'inside any shard_map region' question
    — wrong for routing an axis-less default-group collective, which
    must stay an identity/single-process op.)"""
    a = _axis(group)
    return a is not None and _axis_bound(a)


def _single(group) -> bool:
    g = group or _default_group
    return not _traced_axis_active(g) and g.nranks <= 1


def _multiprocess(group=None) -> bool:
    """True when the eager host-mediated path applies: an N-process world
    (launcher-spawned CPU simulation or a multi-host pod, one rank per
    process) AND the collective spans the WHOLE world. The coordination-
    service primitives are global, so a subgroup call must not enter them
    — members would hang waiting for non-members (and sums would include
    outsiders)."""
    try:
        n = jax.process_count()
    except Exception:
        return False
    if n <= 1:
        return False
    g = group or _default_group
    if g.ranks and len(g.ranks) not in (0, n):
        raise RuntimeError(
            "eager host-mediated collectives only support the WORLD "
            f"group ({n} processes); got a subgroup of {len(g.ranks)}. "
            "Run subgroup collectives inside a compiled region over the "
            "group's mesh axis.")
    return True


def _process_gather_np(data):
    """All-gather a process-local array to every process: [P, ...]."""
    import numpy as np
    from jax.experimental import multihost_utils
    # the choke point every eager host-mediated collective funnels
    # through — and the op that HANGS when a peer died. Entry lands in
    # the flight-recorder ring so a stall bundle shows which collective
    # the process never returned from (no-op while uninstalled).
    from ..profiler import flight_recorder as _frec
    _frec.record_event("collective", op="process_allgather",
                       rank=jax.process_index())
    return np.asarray(multihost_utils.process_allgather(
        jnp.asarray(data), tiled=False))


def _raise_eager(op: str, multiprocess_supported: bool = True):
    extra = (" (In a multi-PROCESS job this op does run eagerly, "
             "host-mediated.)" if multiprocess_supported else
             " For host-side point-to-point control traffic use "
             "paddle.distributed.rpc or the *_object collectives.")
    raise RuntimeError(
        f"{op}: eager multi-device collectives are not the TPU data "
        "plane. Run this op inside a compiled region over a mesh axis "
        "(shard_map / fleet.distributed_model / to_static), or use "
        "*_object collectives for host-side control data." + extra)


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    if _traced_axis_active(group):
        name = _axis(group)
        fns = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
               ReduceOp.MIN: lax.pmin,
               ReduceOp.AVG: lambda x, n: lax.pmean(x, n)}
        if op == ReduceOp.PROD:
            out = apply(lambda a: jnp.exp(lax.psum(jnp.log(a), name)),
                        tensor, name="all_reduce_prod")
        else:
            out = apply(lambda a: fns[op](a, name), tensor,
                        name="all_reduce")
        tensor.set_data(out._data, _clear_tape=False)
        tensor._node, tensor._out_idx = out._node, out._out_idx
        return tensor
    if _single(group):
        return tensor
    if _multiprocess(group):
        import numpy as np
        gathered = _process_gather_np(tensor._data)   # [P, ...]
        red = {ReduceOp.SUM: np.sum, ReduceOp.MAX: np.max,
               ReduceOp.MIN: np.min, ReduceOp.PROD: np.prod,
               ReduceOp.AVG: np.mean}[op]
        tensor.set_data(jnp.asarray(red(gathered, axis=0))
                        .astype(tensor._data.dtype))
        return tensor
    _raise_eager("all_reduce")


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    if _traced_axis_active(group):
        name = _axis(group)
        out = apply(lambda a: lax.all_gather(a, name), tensor,
                    name="all_gather")
        n = (group or _default_group).nranks
        from ..ops.manipulation import unbind
        parts = unbind(out, 0)
        if isinstance(tensor_list, list):
            tensor_list.extend(parts)
            return tensor_list
        return parts
    if _single(group):
        if isinstance(tensor_list, list):
            tensor_list.append(tensor)
            return tensor_list
        return [tensor]
    if _multiprocess(group):
        gathered = _process_gather_np(tensor._data)   # [P, ...]
        parts = [Tensor(jnp.asarray(gathered[i]))
                 for i in range(gathered.shape[0])]
        if isinstance(tensor_list, list):
            tensor_list.extend(parts)
            return tensor_list
        return parts
    _raise_eager("all_gather")


def all_gather_object(object_list, obj, group=None):
    """Host-side control-plane gather (checkpoint coordination etc.)."""
    if get_world_size() <= 1:
        object_list.append(obj)
        return object_list
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(jnp.asarray(0))  # barrier
    # object gather via broadcast of pickled payloads is host-count sized;
    # single-host path above covers tests. Multi-host: use jax broadcast.
    import pickle
    import numpy as np
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    sizes = multihost_utils.process_allgather(
        jnp.asarray([payload.size], jnp.int32))
    maxlen = int(np.max(np.asarray(sizes)))
    padded = np.zeros(maxlen, np.uint8)
    padded[: payload.size] = payload
    all_payloads = multihost_utils.process_allgather(jnp.asarray(padded))
    arr = np.asarray(all_payloads)
    for i in range(arr.shape[0]):
        object_list.append(
            pickle.loads(arr[i, : int(np.asarray(sizes)[i, 0])].tobytes()))
    return object_list


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    if _traced_axis_active(group):
        name = _axis(group)
        src = tensor_list
        if isinstance(src, (list, tuple)):
            from ..ops.manipulation import concat
            src = concat(list(src), axis=0)
        out = apply(lambda a: lax.psum_scatter(a, name, tiled=True), src,
                    name="reduce_scatter")
        tensor.set_data(out._data, _clear_tape=False)
        tensor._node, tensor._out_idx = out._node, out._out_idx
        return tensor
    if _single(group):
        src = tensor_list[0] if isinstance(tensor_list, (list, tuple)) \
            else tensor_list
        tensor.set_data(src._data, _clear_tape=False)
        tensor._node, tensor._out_idx = src._node, src._out_idx
        return tensor
    if _multiprocess(group):
        import numpy as np
        parts = tensor_list if isinstance(tensor_list, (list, tuple)) \
            else [tensor_list]
        mine = np.stack([np.asarray(t._data) for t in parts])  # [P, ...]
        gathered = _process_gather_np(mine)                    # [P, P, ..]
        tensor.set_data(jnp.asarray(
            gathered[:, get_rank()].sum(axis=0))
            .astype(tensor._data.dtype))
        return tensor
    _raise_eager("reduce_scatter")


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    if _traced_axis_active(group):
        name = _axis(group)
        from ..ops.manipulation import stack, unbind
        stacked = stack(list(in_tensor_list), axis=0)
        out = apply(lambda a: lax.all_to_all(a, name, split_axis=0,
                                             concat_axis=0, tiled=False),
                    stacked, name="alltoall")
        parts = unbind(out, 0)
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(parts)
            return out_tensor_list
        return parts
    if _single(group):
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(in_tensor_list)
            return out_tensor_list
        return list(in_tensor_list)
    if _multiprocess(group):
        import numpy as np
        mine = np.stack([np.asarray(t._data) for t in in_tensor_list])
        gathered = _process_gather_np(mine)       # [P, P, ...]
        r = get_rank()
        parts = [Tensor(jnp.asarray(gathered[p, r]))
                 for p in range(gathered.shape[0])]
        if isinstance(out_tensor_list, list):
            out_tensor_list.extend(parts)
            return out_tensor_list
        return parts
    _raise_eager("alltoall")


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    if _traced_axis_active(group):
        name = _axis(group)
        out = apply(lambda a: lax.all_to_all(
            a, name, split_axis=0, concat_axis=0, tiled=True),
            in_tensor, name="alltoall_single")
        out_tensor.set_data(out._data, _clear_tape=False)
        out_tensor._node = out._node
        out_tensor._out_idx = out._out_idx
        return out_tensor
    if _single(group):
        out_tensor.set_data(in_tensor._data, _clear_tape=False)
        out_tensor._node = in_tensor._node
        out_tensor._out_idx = in_tensor._out_idx
        return out_tensor
    if _multiprocess(group):
        import numpy as np
        n = jax.process_count()
        a = np.asarray(in_tensor._data)
        if a.shape[0] % n:
            raise ValueError(
                f"alltoall_single: dim 0 ({a.shape[0]}) not divisible by "
                f"world size {n}")
        mine = a.reshape((n, a.shape[0] // n) + a.shape[1:])
        gathered = _process_gather_np(mine)        # [P, P, k, ...]
        out = np.concatenate(
            [gathered[p, get_rank()] for p in range(n)], axis=0)
        out_tensor.set_data(jnp.asarray(out).astype(
            out_tensor._data.dtype))
        return out_tensor
    _raise_eager("alltoall_single")


def broadcast(tensor, src=0, group=None, sync_op=True):
    if _traced_axis_active(group):
        name = _axis(group)
        g = group or _default_group
        src_local = g.get_group_rank(src) if g.ranks else src

        def fn(a):
            # select src's value on every member: gather then index
            return lax.all_gather(a, name)[src_local]
        out = apply(fn, tensor, name="broadcast")
        tensor.set_data(out._data, _clear_tape=False)
        tensor._node, tensor._out_idx = out._node, out._out_idx
        return tensor
    if _single(group):
        return tensor
    if _multiprocess(group):
        from jax.experimental import multihost_utils
        out = multihost_utils.broadcast_one_to_all(
            tensor._data, is_source=get_rank() == src)
        tensor.set_data(jnp.asarray(out))
        return tensor
    _raise_eager("broadcast")


def broadcast_object_list(object_list, src=0, group=None):
    if get_world_size() <= 1:
        return object_list
    import pickle
    import numpy as np
    from jax.experimental import multihost_utils
    # broadcast_one_to_all needs identical shapes on every host:
    # broadcast the byte length first, then the zero-padded payload
    if get_rank() == src:
        payload = np.frombuffer(pickle.dumps(object_list), np.uint8)
    else:
        payload = np.zeros(0, np.uint8)
    n = multihost_utils.broadcast_one_to_all(
        jnp.asarray([payload.size], jnp.int32),
        is_source=get_rank() == src)
    total = int(np.asarray(n)[0])
    padded = np.zeros(total, np.uint8)
    padded[: payload.size] = payload[:total]
    out = multihost_utils.broadcast_one_to_all(
        jnp.asarray(padded), is_source=get_rank() == src)
    if get_rank() != src:
        object_list[:] = pickle.loads(np.asarray(out).tobytes())
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # on TPU a reduce-to-root inside SPMD is just an all_reduce (cheap over
    # ICI; avoids divergent programs)
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _traced_axis_active(group):
        name = _axis(group)
        from ..ops.manipulation import stack
        stacked = stack(list(tensor_list), axis=0)

        def fn(a):
            # every rank holds the full list (SPMD); pick own slice
            idx = lax.axis_index(name)
            return lax.dynamic_index_in_dim(a, idx, 0, keepdims=False)
        out = apply(fn, stacked, name="scatter")
        tensor.set_data(out._data, _clear_tape=False)
        tensor._node, tensor._out_idx = out._node, out._out_idx
        return tensor
    if _single(group):
        src_t = tensor_list[0]
        tensor.set_data(src_t._data, _clear_tape=False)
        tensor._node, tensor._out_idx = src_t._node, src_t._out_idx
        return tensor
    if _multiprocess(group):
        payload = [None]
        if get_rank() == src:
            import numpy as np
            payload = [np.stack([np.asarray(t._data)
                                 for t in tensor_list])]
        broadcast_object_list(payload, src=src, group=group)
        tensor.set_data(jnp.asarray(payload[0][get_rank()]))
        return tensor
    _raise_eager("scatter")


def send(tensor, dst=0, group=None, sync_op=True):
    if _traced_axis_active(group):
        raise RuntimeError(
            "point-to-point send/recv inside traced code should use "
            "lax.ppermute via paddle_tpu.distributed.fleet p2p helpers")
    if _single(group):
        _p2p_buf.append(tensor)
        return
    _raise_eager("send", multiprocess_supported=False)


_p2p_buf: list = []


def recv(tensor, src=0, group=None, sync_op=True):
    if _single(group):
        if _p2p_buf:
            src_t = _p2p_buf.pop(0)
            tensor.set_data(src_t._data, _clear_tape=False)
        return tensor
    _raise_eager("recv", multiprocess_supported=False)


class _Work:
    def wait(self):
        return True

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group)
    return _Work()


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group)
    return _Work()


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    works = []
    for op in p2p_op_list:
        works.append(op.op(op.tensor, op.peer, op.group))
    return works


def barrier(group=None):
    if get_world_size() <= 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices("paddle_tpu_barrier")


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(tensor._data)
    return tensor


class stream:
    """``paddle.distributed.stream`` namespace: stream-targeted variants.
    XLA owns scheduling on TPU; these alias the defaults."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    broadcast = staticmethod(broadcast)
    reduce = staticmethod(reduce)
    scatter = staticmethod(scatter)
    send = staticmethod(send)
    recv = staticmethod(recv)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather to the dst rank. SPMD/TPU note: inside a traced collective
    this is an all_gather (every shard holds the result — a root-only
    gather has no cheaper lowering over ICI); single-process it fills
    gather_list from the tensor."""
    if gather_list is None:
        gather_list = []
    if _traced_axis_active(group) or not _single(group):
        parts = all_gather([], tensor, group=group)
        gather_list.extend(parts if isinstance(parts, list) else [parts])
        return gather_list
    gather_list.append(tensor)
    return gather_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Host-side object scatter (control plane): broadcast the src list,
    each rank keeps its group-rank element."""
    if get_world_size() <= 1:
        out_object_list[:] = [in_object_list[0]] if in_object_list else []
        return out_object_list
    payload = list(in_object_list) if get_rank() == src \
        and in_object_list is not None else []
    broadcast_object_list(payload, src=src, group=group)
    g = group or _default_group
    r = g.ranks.index(get_rank()) if g.ranks and get_rank() in g.ranks \
        else get_rank()
    out_object_list[:] = [payload[r]]
    return out_object_list


def destroy_process_group(group=None):
    """Tear down process-group state (paddle parity). PJRT owns the real
    collectives context; this clears the python-side env/topology so a
    fresh init_parallel_env starts clean."""
    from . import env as _env
    _env._initialized = False
    from .fleet import base as _fb
    _fb.fleet._hcg = None
    _fb.fleet._topology = None
    _fb.fleet._is_initialized = False


def get_backend(group=None) -> str:
    """The collective backend name ('xla': ICI/DCN collectives compiled
    by XLA — the role NCCL plays in the reference)."""
    return "xla"


def is_available() -> bool:
    return True


__all__ += ["gather", "scatter_object_list", "destroy_process_group",
            "get_backend", "is_available"]
