"""``paddle.distributed.utils`` helpers (upstream parity, minimal)."""

from __future__ import annotations

__all__ = ["get_available_device", "global_scatter", "global_gather"]


def get_available_device():
    """Device ids visible to this process (TPU chips, else CPU)."""
    import jax

    return [str(i) for i in range(jax.local_device_count())]


def global_scatter(x, local_count, global_count, group=None):
    raise NotImplementedError(
        "utils.global_scatter is the GPU MoE dispatch primitive; on TPU "
        "use paddle_tpu.ops.moe (all-to-all dispatch inside the compiled "
        "step)")


def global_gather(x, local_count, global_count, group=None):
    raise NotImplementedError(
        "utils.global_gather is the GPU MoE combine primitive; on TPU "
        "use paddle_tpu.ops.moe")
