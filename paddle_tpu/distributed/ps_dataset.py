"""``paddle.distributed.{InMemoryDataset, QueueDataset}`` — file-fed
training datasets (upstream python/paddle/distributed/fleet/dataset/,
UNVERIFIED; reference mount empty).

Reference role: C++ DataFeed pipelines streaming slot-parsed text through
an optional shell ``pipe_command`` into the parameter-server trainers.
TPU-native stance: the PS runtime is out of scope (SURVEY §2.3), but the
dataset surface is useful standalone — these read whitespace-separated
slot files (optionally through a real ``pipe_command`` subprocess),
batch records host-side, and iterate numpy batches compatible with a
train loop. InMemoryDataset additionally materializes + shuffles."""

from __future__ import annotations

import random
import subprocess

import numpy as np

__all__ = ["QueueDataset", "InMemoryDataset"]


class _DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._use_var = []
        self._pipe_command = None
        self._input_type = 0
        self._filelist: list[str] = []

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self._batch_size = int(batch_size)
        self._thread_num = int(thread_num)
        self._use_var = list(use_var or [])
        self._pipe_command = pipe_command
        self._input_type = input_type
        return self

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _update_settings(self, **kwargs):
        for k, v in kwargs.items():
            attr = "_" + k
            if hasattr(self, attr):
                setattr(self, attr, v)

    update_settings = _update_settings

    def _read_records(self):
        """Yield one parsed record per input line, streamed (slot files
        can be huge — never materialize a whole file): whitespace-
        separated fields, numeric where possible."""
        for path in self._filelist:
            if self._pipe_command:
                with open(path, "rb") as fh:
                    proc = subprocess.Popen(
                        self._pipe_command, shell=True, stdin=fh,
                        stdout=subprocess.PIPE, text=True)
                    finished = False
                    try:
                        yield from self._parse_lines(proc.stdout)
                        finished = True
                    finally:
                        proc.stdout.close()
                        rc = proc.wait()
                        # early iterator exit kills the child via SIGPIPE
                        # (rc -13/141) — that's normal teardown, only a
                        # fully-consumed stream must have exited cleanly
                        if finished and rc != 0:
                            raise subprocess.CalledProcessError(
                                rc, self._pipe_command)
            else:
                with open(path) as fh:
                    yield from self._parse_lines(fh)

    @staticmethod
    def _parse_lines(lines):
        for line in lines:
            if not line.strip():
                continue
            fields = []
            for tok in line.split():
                try:
                    fields.append(int(tok))
                except ValueError:
                    try:
                        fields.append(float(tok))
                    except ValueError:
                        fields.append(tok)
            yield fields

    def _batched(self, records):
        batch = []
        for rec in records:
            batch.append(rec)
            if len(batch) == self._batch_size:
                yield self._to_batch(batch)
                batch = []
        if batch:
            yield self._to_batch(batch)

    @staticmethod
    def _to_batch(records):
        try:
            return np.asarray(records)
        except ValueError:  # ragged records stay a list
            return records

    def __iter__(self):
        return self._batched(self._read_records())


class QueueDataset(_DatasetBase):
    """Streaming dataset: records flow straight from the filelist."""


class InMemoryDataset(_DatasetBase):
    """Load-then-train dataset with shuffle support."""

    def __init__(self):
        super().__init__()
        self._records: list | None = None

    def load_into_memory(self):
        self._records = list(self._read_records())

    def local_shuffle(self):
        if self._records is None:
            raise RuntimeError("call load_into_memory() first")
        random.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-host build: global == local
        self.local_shuffle()

    def release_memory(self):
        self._records = None

    def get_memory_data_size(self, fleet=None):
        return len(self._records or [])

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    def __iter__(self):
        if self._records is None:
            return super().__iter__()
        return self._batched(iter(self._records))
