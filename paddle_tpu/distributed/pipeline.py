"""Compiled SPMD pipeline parallelism — the engine behind
``fleet.meta_parallel.PipelineParallel`` at pp_degree > 1.

Reference parity: fleet ``pipeline_parallel.py`` + ``pp_utils/
p2p_communication.py`` (SURVEY.md §2.3 PP row, §3.4): FThenB / 1F1B
schedules, NCCL p2p of activations between stage *processes*, microbatch
accumulation. Reference mount was empty; no file:line cites.

TPU-native design (SURVEY.md §7 "hard parts" #1) — NOT a port:

- All stages live in ONE compiled program, SPMD over the mesh's 'pipe'
  axis. Per-stage weights are stacked along a leading stage dimension
  sharded over 'pipe', so each device row holds exactly its stage's
  weights.
- The schedule is a ``lax.scan`` over T = M + S - 1 ticks. Every tick,
  every stage runs one microbatch slot and hands its activation to the
  next stage with a single ``lax.ppermute`` hop (a neighbor transfer over
  ICI — the role NCCL p2p plays on GPU). Stage 0 ingests a fresh
  microbatch per tick; the last stage emits into an output buffer.
- This realizes the fill/steady/drain structure of FThenB: bubble
  fraction (S-1)/(M+S-1), same as GPipe. The *backward* schedule is jax
  reverse-mode through the scan: the transposed ppermute runs the ring
  backwards — a compiled backward pipeline with the same bubble. 1F1B's
  memory advantage is recovered the XLA way with rematerialization
  (``remat='stage'`` recomputes each stage's forward during backward so
  only the S boundary activations per microbatch stay alive, not every
  layer intermediate).
- Interleaved/virtual-stage (Megatron "virtual pipeline") is the
  ``n_virtual > 1`` path: the model is split into L = S*V chunks laid
  out round-robin (chunk c lives on device c % S as its local chunk
  c // S), so one ``ppermute`` hop per tick still moves every
  activation to its next chunk — the ring simply wraps V times.
  Microbatches are processed in groups of S (the classic interleaved
  constraint), giving the collision-free closed-form schedule
  t(m, c) = (m // S)·S·V + (c // S)·S + (m % S) + (c % S): per-device
  bubble (S-1)/(M·V) of total ticks vs (S-1)/(M+S-1) for FThenB — the
  1/V bubble shrink Megatron's interleaved schedule buys, in one
  compiled scan.
  True 1F1B and zero-bubble (ZB-H1) with explicit B/W scheduling live
  in ``zero_bubble.py`` (table-driven tick machine over the same
  ppermute ring).

Everything is shape-static; ``pipeline_spmd`` must run inside a
partial-manual ``shard_map(axis_names={'pipe'})`` region (see
``run_pipeline`` for the global-view entry point that sets this up).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.jax_compat import shard_map as _shard_map
from jax import lax

__all__ = ["pipeline_spmd", "run_pipeline"]


def _vary(x, axis_name, like=None):
    """Mark ``x`` device-varying over ``axis_name`` plus every axis that
    ``like`` already varies on (e.g. 'sep' when the microbatch stream is
    context-sharded inside a 5D pp x sep region) — scan carries must
    type-match their ppermute'd outputs."""
    from ..framework._vma import pvary_missing
    return pvary_missing(x, (axis_name,), like=like)


def pipeline_spmd(stage_fn, stage_params, x_micro, axis_name,
                  n_virtual=1, remat=None):
    """Pipeline a stack of stages over mesh axis ``axis_name``.

    stage_fn(params_one_stage, x) -> y — shape/dtype-preserving stage
      compute.
    stage_params: pytree; every leaf has leading dim S (the per-stage
      stack), sharded over 'pipe' outside this manual region. Inside,
      each device sees [1, ...] local leaves. With n_virtual=V > 1,
      leaves are instead [V, S, ...] with dim 1 sharded over 'pipe'
      (locally [V, 1, ...]): device d's local chunk v is global chunk
      v*S + d (see _pipeline_interleaved).
    x_micro: [M, ...] microbatched stage-0 inputs (replicated over pipe).
    remat: None | 'stage' — rematerialize each stage call in backward.
    Returns [M, ...] last-stage outputs (replicated over the pipe axis).
    """
    if n_virtual != 1:
        return _pipeline_interleaved(stage_fn, stage_params, x_micro,
                                     axis_name, n_virtual, remat)
    S = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]

    def one_stage(x):
        p = jax.tree.map(lambda q: lax.index_in_dim(q, 0, 0, False),
                         stage_params)
        return stage_fn(p, x)

    if remat == "stage":
        from ..incubate.recompute import checkpoint_with_policy
        one_stage = checkpoint_with_policy(one_stage)

    perm = [(i, (i + 1) % S) for i in range(S)]
    T = M + S - 1

    def tick(carry, t):
        act, outbuf = carry
        inp_idx = jnp.clip(t, 0, M - 1)
        x0 = lax.dynamic_index_in_dim(x_micro, inp_idx, 0, False)
        inp = jnp.where(idx == 0, _vary(x0, axis_name), act)
        out = one_stage(inp)
        emit_t = t - (S - 1)
        emit_ok = (idx == S - 1) & (emit_t >= 0)
        slot = jnp.clip(emit_t, 0, M - 1)
        cur = lax.dynamic_index_in_dim(outbuf, slot, 0, False)
        new = jnp.where(emit_ok, out, cur)
        outbuf = lax.dynamic_update_index_in_dim(outbuf, new, slot, 0)
        act = lax.ppermute(out, axis_name, perm)
        return (act, outbuf), None

    act0 = _vary(jnp.zeros(mb_shape, x_micro.dtype), axis_name,
                 like=x_micro)
    outbuf0 = _vary(jnp.zeros((M,) + mb_shape, x_micro.dtype), axis_name,
                    like=x_micro)
    (act, outbuf), _ = lax.scan(tick, (act0, outbuf0), jnp.arange(T))
    # only the last stage's buffer is real; replicate it over the axis
    mask = (idx == S - 1).astype(outbuf.dtype)
    return lax.psum(outbuf * mask, axis_name)


def _pipeline_interleaved(stage_fn, stage_params, x_micro, axis_name,
                          n_virtual, remat=None):
    """Interleaved (virtual-stage) schedule: Megatron-style 1/V bubble.

    stage_params leaves are locally [V, 1, ...] (globally [V, S, ...]
    with dim 1 sharded over the pipe axis): device d's local chunk v is
    global chunk  c = v*S + d  — the round-robin chunk placement of the
    reference's interleaved-1F1B (fleet pipeline_parallel.py virtual-pp,
    UNVERIFIED — mount empty).

    Schedule (see module docstring): microbatches run in G groups of S;
    device d at tick t works on slot t' = t - d, decoded as
    group g = t' // (S*V), chunk v = (t' % (S*V)) // S and microbatch
    m = g*S + t' % S. Each tick's output takes ONE ppermute hop to the
    next device, which holds the next global chunk; outputs of the last
    chunk (on device S-1) wrap around to device 0, which banks them
    into the output buffer instead of consuming them.
    """
    S = lax.psum(1, axis_name)
    d = lax.axis_index(axis_name)
    V = int(n_virtual)
    M = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    G = -(-M // S)  # microbatch groups of S (ragged last group = bubble)
    T = G * S * V + S  # +S: drain final-chunk outputs back to device 0

    def one_chunk(p, x):
        return stage_fn(p, x)

    if remat == "stage":
        from ..incubate.recompute import checkpoint_with_policy
        one_chunk = checkpoint_with_policy(one_chunk)

    perm = [(i, (i + 1) % S) for i in range(S)]
    ring = S * V

    def decode(tp):
        g = tp // ring
        r = tp % ring
        return g, r // S, g * S + r % S  # group, chunk, microbatch

    def tick(carry, t):
        act, outbuf = carry
        # 1) bank an arriving final-chunk output (device 0 only): the
        #    carry is device S-1's output from tick t-1 = slot t-S.
        em_tp = t - S
        _, em_v, em_m = decode(jnp.maximum(em_tp, 0))
        em_ok = ((d == 0) & (em_tp >= 0) & (em_v == V - 1)
                 & (em_m < M))
        slot = jnp.clip(em_m, 0, M - 1)
        cur = lax.dynamic_index_in_dim(outbuf, slot, 0, False)
        outbuf = lax.dynamic_update_index_in_dim(
            outbuf, jnp.where(em_ok, act, cur), slot, 0)
        # 2) this tick's work unit
        tp = t - d
        g, v, m = decode(jnp.maximum(tp, 0))
        x0 = lax.dynamic_index_in_dim(
            x_micro, jnp.clip(m, 0, M - 1), 0, False)
        fresh = (d == 0) & (v == 0)
        # x0 is indexed by the device-dependent m, so it is already
        # axis-varying — no pcast needed (unlike the FThenB path).
        inp = jnp.where(fresh, x0, act)
        p = jax.tree.map(
            lambda q: lax.index_in_dim(
                lax.dynamic_index_in_dim(q, jnp.clip(v, 0, V - 1), 0,
                                         False), 0, 0, False),
            stage_params)
        out = one_chunk(p, inp)
        act = lax.ppermute(out, axis_name, perm)
        return (act, outbuf), None

    act0 = _vary(jnp.zeros(mb_shape, x_micro.dtype), axis_name,
                 like=x_micro)
    outbuf0 = _vary(jnp.zeros((M,) + mb_shape, x_micro.dtype), axis_name,
                    like=x_micro)
    (act, outbuf), _ = lax.scan(tick, (act0, outbuf0), jnp.arange(T))
    mask = (d == 0).astype(outbuf.dtype)
    return lax.psum(outbuf * mask, axis_name)


def run_pipeline(stage_fn, stacked_params, x_micro, mesh, axis_name="pipe",
                 n_virtual=1, remat=None, extra_axes=(), x_spec=None,
                 param_specs=None):
    """Global-view entry: partial-manual shard_map over the pipe axis
    (other mesh axes stay under GSPMD). ``stacked_params`` leaves are
    [S, ...] arrays sharded on dim 0 over 'pipe' (n_virtual == 1), or
    [V, S, ...] sharded on dim 1 (interleaved: global chunk v*S + d is
    device d's local chunk v).

    extra_axes/x_spec — the 5D pp x sep composition: ``extra_axes``
    names additional mesh axes to bind manually alongside 'pipe'
    (e.g. ('sep',)), and ``x_spec`` shards the microbatch stream over
    them (e.g. P(None, None, 'sep') — sequence dim context-sharded).
    Inside the region, stage_fn's attention issues the K/V ring directly
    on the bound 'sep' axis (``sep_attention_manual``); the same spec
    reassembles the output, so the epilogue/loss still see the full
    logical sequence under GSPMD. Parameter cotangents are psum'd over
    the extra axes automatically by shard_map's reverse-mode (their
    in_specs don't mention 'sep', so the transpose inserts the sum)."""
    from jax.sharding import PartitionSpec as P

    if param_specs is not None:
        # caller-supplied per-leaf specs (same pytree structure as
        # stacked_params) — e.g. keeping an expert-weight bank's expert
        # dim sharded over its own mesh axis through the manual region
        pspecs = param_specs
    elif n_virtual == 1:
        pspecs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    else:
        pspecs = jax.tree.map(lambda _: P(None, axis_name),
                              stacked_params)
    if x_spec is None:
        x_spec = P()

    f = _shard_map(
        functools.partial(pipeline_spmd, stage_fn, axis_name=axis_name,
                          n_virtual=n_virtual, remat=remat),
        mesh=mesh,
        in_specs=(pspecs, x_spec),
        out_specs=x_spec,
        axis_names={axis_name, *extra_axes},
    )
    return f(stacked_params, x_micro)
