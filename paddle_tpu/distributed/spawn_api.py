"""``paddle.distributed.spawn`` — multi-process launcher-as-a-function
(upstream python/paddle/distributed/spawn.py, UNVERIFIED).

Spawns ``nprocs`` python processes running ``func(*args)`` with the
paddle rank env set, CPU-pinned jax (the launcher's simulation mode —
one process drives all TPU chips in real runs, so multi-proc spawn is
the CPU/Gloo-role path)."""

from __future__ import annotations

import multiprocessing
import os

__all__ = ["spawn"]


def _entry(func, rank, nprocs, args):
    os.environ.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_RANK": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_WORLD_SIZE": str(nprocs),
        "JAX_PLATFORMS": "cpu",
    })
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    func(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    """Run ``func(*args)`` in ``nprocs`` fresh processes. Returns the
    context (list of processes); with ``join=True`` waits and raises if
    any worker failed."""
    ctx = multiprocessing.get_context("spawn")
    procs = []
    for rank in range(int(nprocs)):
        p = ctx.Process(target=_entry, args=(func, rank, nprocs, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [i for i, p in enumerate(procs) if p.exitcode != 0]
        if bad:
            raise RuntimeError(
                f"paddle.distributed.spawn: ranks {bad} exited nonzero")
    return procs
