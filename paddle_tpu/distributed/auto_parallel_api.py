"""Auto-parallel mid-layer — ``dist.to_static`` / ``DistModel`` / Strategy
parity (UNVERIFIED paths python/paddle/distributed/auto_parallel/).

The reference's static SPMD planner (completion pass over spmd_rules +
reshard) is GSPMD's job here: ``dist.to_static`` functionalizes the train
step exactly like ``paddle_tpu.jit.to_static`` — parameters already carry
NamedSharding placements, so XLA propagates shardings op-by-op and inserts
collectives/reshards."""

from __future__ import annotations

from ..framework.core import Tensor

__all__ = ["Strategy", "DistAttr", "DistModel", "to_static",
           "unshard_dtensor"]


class Strategy:
    def __init__(self, config=None):
        config = config or {}
        self.sharding = _Cfg(config.get("sharding", {}))
        self.fused_passes = _Cfg(config.get("fused_passes", {}))
        self.gradient_merge = _Cfg(config.get("gradient_merge", {}))
        self.pipeline = _Cfg(config.get("pipeline", {}))
        self.amp = _Cfg(config.get("amp", {}))


class _Cfg:
    def __init__(self, d):
        self.enable = d.get("enable", False)
        self.__dict__.update(d)


class DistAttr:
    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs


class DistModel:
    """Wraps (layer, loader, loss, optimizer) into compiled train/eval
    steps — ``dist.to_static`` return object parity."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None):
        self.network = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._mode = "train"
        from ..jit.to_static_api import StaticFunction
        self._train_step = StaticFunction(self._train_impl)
        self._eval_step = StaticFunction(self._eval_impl)

    def _train_impl(self, *inputs):
        *xs, label = inputs
        out = self.network(*xs)
        loss = self._loss(out, label)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return loss

    def _eval_impl(self, *inputs):
        *xs, label = inputs
        out = self.network(*xs)
        return self._loss(out, label)

    def train(self):
        self._mode = "train"
        self.network.train()

    def eval(self):
        self._mode = "eval"
        self.network.eval()

    def __call__(self, *inputs):
        if self._mode == "train":
            return self._train_step(*inputs)
        return self._eval_step(*inputs)

    def state_dict(self, mode="all"):
        sd = dict(self.network.state_dict())
        if mode in ("all", "opt") and self._optimizer is not None:
            sd.update(self._optimizer.state_dict())
        return sd

    def set_state_dict(self, state_dict):
        self.network.set_state_dict(state_dict)
        if self._optimizer is not None:
            self._optimizer.set_state_dict(state_dict)

    def dist_main_program(self, mode=None):
        return None


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """``dist.to_static`` — returns a DistModel with compiled steps."""
    return DistModel(layer, loader, loss, optimizer, strategy)


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Gather a sharded tensor to a replicated dense tensor."""
    import jax
    import numpy as np
    data = dist_tensor._data
    if isinstance(data, jax.Array):
        out = jax.device_get(data)
        return Tensor(out)
    return Tensor(np.asarray(data))
