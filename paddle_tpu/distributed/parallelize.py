"""``paddle.distributed.parallelize`` — the paddle-3.x one-call
auto-parallel API (upstream ``python/paddle/distributed/auto_parallel/
intermediate/parallelize.py``, UNVERIFIED; reference mount empty).

TPU-native: a parallelize_plan maps sublayer-name patterns to placement
markers (ColWiseParallel / RowWiseParallel / PrepareLayerInput/Output);
applying the plan device_puts the matched weights with a NamedSharding
over the mesh's 'model' axis and GSPMD compiles the collectives. dp
sharding needs no model rewrite (batch sharding at the input is enough);
pp is served by the PipelineLayer engine, not this entry point.
"""

from __future__ import annotations

import fnmatch

import jax
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["parallelize", "ColWiseParallel", "RowWiseParallel",
           "PrepareLayerInput", "PrepareLayerOutput"]


class _Placement:
    pass


class ColWiseParallel(_Placement):
    """Linear weight [in, out]: shard the OUT dim; Embedding weight
    [V, D]: shard the D dim (upstream semantics)."""

    def spec_for(self, param_name, shape):
        if param_name.endswith("bias") and len(shape) == 1:
            return PartitionSpec("model")
        if len(shape) == 2:
            return PartitionSpec(None, "model")
        return PartitionSpec()


class RowWiseParallel(_Placement):
    """Linear weight [in, out]: shard the IN dim; Embedding weight
    [V, D]: shard the vocab dim."""

    def spec_for(self, param_name, shape):
        if len(shape) == 2:
            return PartitionSpec("model", None)
        return PartitionSpec()   # bias replicated (output is full)


class PrepareLayerInput(_Placement):
    def __init__(self, fn=None):
        self.fn = fn

    def spec_for(self, param_name, shape):
        return None


class PrepareLayerOutput(_Placement):
    def __init__(self, fn=None):
        self.fn = fn

    def spec_for(self, param_name, shape):
        return None


def _get_mesh(config):
    from .fleet import base as fb

    mp = 0
    if config and "mp_config" in config:
        # degree lives inside mp_config (upstream layout); 0 = all devices
        mp = int((config.get("mp_config") or {}).get("mp_degree", 0)) or 0
    if fb.fleet._hcg is None:
        strategy = fb.DistributedStrategy()
        n = jax.device_count()
        strategy.hybrid_configs = {"dp_degree": -1,
                                   "mp_degree": mp or n,
                                   "pp_degree": 1, "sharding_degree": 1,
                                   "sep_degree": 1, "ep_degree": 1}
        fb.fleet.init(strategy=strategy)
    return fb.fleet._hcg.global_mesh


def parallelize(model, optimizer=None, mesh=None, config=None):
    """Apply a parallelize_plan to ``model`` (and wrap ``optimizer`` for
    sharding when dp_config asks). Returns (model, optimizer)."""
    config = config or {}
    plan = (config.get("mp_config") or {}).get("parallelize_plan") or {}
    bad = [v for v in plan.values() if not isinstance(v, _Placement)]
    if bad:
        raise TypeError(
            f"parallelize_plan values must be placements, got {bad[:3]}")
    if plan:
        the_mesh = mesh if mesh is not None and hasattr(mesh, "shape") \
            else _get_mesh(config)
        matched = set()
        for lname, layer in model.named_sublayers():
            for pattern, placement in plan.items():
                if fnmatch.fnmatch(lname, pattern) or lname == pattern:
                    matched.add(pattern)
                    for pname, p in layer.named_parameters(
                            include_sublayers=False):
                        spec = placement.spec_for(pname, p.shape)
                        if spec is None:
                            continue
                        p.set_data(jax.device_put(
                            p._data, NamedSharding(the_mesh, spec)))
                        p.is_distributed = True
        unmatched = set(plan) - matched
        if unmatched:
            import warnings

            warnings.warn(
                f"parallelize: plan patterns matched no sublayer: "
                f"{sorted(unmatched)}")
    if optimizer is not None and (config.get("dp_config") or {}).get(
            "sharding_level"):
        from .fleet.sharding import DygraphShardingOptimizer
        from .fleet import base as fb

        if fb.fleet._hcg is None:
            _get_mesh(config)   # dp-only configs still need the mesh
        optimizer = DygraphShardingOptimizer(optimizer, fb.fleet._hcg)
        optimizer._place_new_state()
    return model, optimizer
