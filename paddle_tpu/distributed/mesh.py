"""Process mesh + placements — the auto-parallel surface.

Reference role: ``dist.ProcessMesh`` + ``Shard/Replicate/Partial``
placements + DistTensor (SURVEY.md §2.1 DistTensor row, §2.3 auto-parallel).
TPU-native: a ProcessMesh IS a ``jax.sharding.Mesh``; placements desugar to
``jax.sharding.NamedSharding`` PartitionSpecs, and GSPMD does rule
propagation + reshard — the things the reference implements by hand in
``phi/infermeta/spmd_rules`` and reshard functions."""

from __future__ import annotations

import weakref

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..framework.core import Tensor

# DistTensor metadata: the SOURCE OF TRUTH is the underlying jax
# array's NamedSharding — placements/process_mesh are RE-DERIVED lazily
# in the property getter, so the metadata survives everything the array
# survives: ``y = x + 0``, reshapes, state_dict round-trips, optimizer
# rebinds (advisor r5; the id()-keyed side table lost it on any derived
# tensor). A side table still exists for EXPLICIT annotations the
# sharding cannot encode (e.g. ``Partial``) and takes precedence; it is
# keyed by id() with weakref.finalize cleanup, NOT a WeakKeyDictionary:
# weak-key lookups compare colliding keys with ==, and Tensor.__eq__ is
# elementwise. Plain Tensors (no NamedSharding, no annotation) report
# None, matching the reference's "dense tensor has no dist attr".
_dist_attr: dict = {}


def _named_sharding_of(t):
    try:
        sh = t._data.sharding     # tracers may refuse the attribute
    except Exception:
        return None
    return sh if isinstance(sh, NamedSharding) else None


def _derive_placements(ns: NamedSharding):
    names = list(ns.mesh.axis_names)
    placements = [Replicate()] * len(names)
    for tdim, entry in enumerate(ns.spec):
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, tuple) else (entry,)):
            placements[names.index(ax)] = Shard(tdim)
    return placements


def _derive_process_mesh(ns: NamedSharding):
    return ProcessMesh(np.asarray(ns.mesh.device_ids),
                       list(ns.mesh.axis_names))


def _mk_dist_prop(key):
    def get(self):
        rec = _dist_attr.get(id(self))
        if rec is not None and key in rec:
            return rec[key]
        ns = _named_sharding_of(self)
        if ns is None:
            return None
        return _derive_placements(ns) if key == "placements" \
            else _derive_process_mesh(ns)

    def set_(self, value):
        k = id(self)
        rec = _dist_attr.get(k)
        if rec is None:
            rec = _dist_attr[k] = {}
            weakref.finalize(self, _dist_attr.pop, k, None)
        rec[key] = value

    return property(get, set_)


Tensor.placements = _mk_dist_prop("placements")
Tensor.process_mesh = _mk_dist_prop("process_mesh")
Tensor.is_dist = lambda self: (_dist_attr.get(id(self)) is not None
                               or _named_sharding_of(self) is not None)

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "shard_op",
           "reshard", "dtensor_from_fn", "shard_layer", "get_mesh",
           "set_mesh", "auto_mesh"]


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("S", self.dim))

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("R")

    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return True

    def is_partial(self):
        return False


class Partial(Placement):
    """Pending-reduction placement. GSPMD materializes partial sums
    implicitly; we reduce eagerly on reshard to Replicate."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return True


class ProcessMesh:
    """Named device mesh. ``mesh`` may be an nd array of device ids (paddle
    style); on single-host TPU we map ids onto jax.devices()."""

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is None and shape is not None:
            mesh = np.arange(int(np.prod(shape))).reshape(shape)
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self.dim_names = list(dim_names)
        self._ids = arr
        devices = jax.devices()
        if arr.size > len(devices):
            raise ValueError(
                f"mesh needs {arr.size} devices, have {len(devices)} "
                "(use XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "with JAX_PLATFORMS=cpu to simulate)")
        dev_arr = np.empty(arr.shape, dtype=object)
        for idx, pid in np.ndenumerate(arr):
            dev_arr[idx] = devices[int(pid)]
        self.jax_mesh = Mesh(dev_arr, tuple(self.dim_names))

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    def get_dim_size(self, name):
        return self._ids.shape[self.dim_names.index(name)]

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, " \
               f"dim_names={self.dim_names})"

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self.dim_names == other.dim_names
                and np.array_equal(self._ids, other._ids))

    def __enter__(self):
        set_mesh(self)
        return self

    def __exit__(self, *exc):
        return False


_global_mesh: ProcessMesh | None = None


def set_mesh(mesh: ProcessMesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> ProcessMesh | None:
    return _global_mesh


def auto_mesh(dim_names=("data",), shape=None) -> ProcessMesh:
    """Build a mesh over all visible devices."""
    n = jax.device_count()
    if shape is None:
        shape = [n] + [1] * (len(dim_names) - 1)
    return ProcessMesh(np.arange(n).reshape(shape), list(dim_names))


def _partition_spec(placements, ndim, mesh: ProcessMesh):
    spec = [None] * ndim
    for axis_name, placement in zip(mesh.dim_names, placements):
        if isinstance(placement, Shard):
            d = placement.dim
            if spec[d] is None:
                spec[d] = axis_name
            elif isinstance(spec[d], tuple):
                spec[d] = spec[d] + (axis_name,)
            else:
                spec[d] = (spec[d], axis_name)
    return PartitionSpec(*spec)


def shard_tensor(x, mesh: ProcessMesh, placements, dtype=None,
                 stop_gradient=None) -> Tensor:
    """``dist.shard_tensor`` — place x on the mesh with the given
    placements. Returns a Tensor whose jax.Array carries NamedSharding
    (a DistTensor in reference terms)."""
    if not isinstance(x, Tensor):
        x = Tensor(x)
    ns = NamedSharding(mesh.jax_mesh,
                       _partition_spec(placements, x.ndim, mesh))
    data = jax.device_put(x._data, ns)
    out = Tensor(data, stop_gradient=x.stop_gradient
                 if stop_gradient is None else stop_gradient)
    out.persistable = x.persistable
    out.name = x.name
    out.placements = list(placements)
    out.process_mesh = mesh
    return out


def reshard(x: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """``dist.reshard`` — change placements; XLA emits the collectives
    (the reference's RToS/PToR/... reshard functions, for free)."""
    has_partial = any(isinstance(p, Partial) for p in placements)
    if has_partial:
        raise ValueError("reshard target cannot be Partial")
    return shard_tensor(x, mesh, placements)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def shard_op(op_fn, process_mesh, in_placements=None,
             out_placements=None):
    """``dist.shard_op`` — wrap a callable so its tensor inputs/outputs
    are annotated with the given placements on ``process_mesh`` (the
    reference marks the op for the SPMD planner; here the annotation IS
    the plan — GSPMD propagates from it)."""
    def _place(t, placements):
        if placements is None or not isinstance(t, Tensor):
            return t
        return shard_tensor(t, process_mesh, placements)

    def _per_item(placements_arg):
        # accept [[Shard(0)], [Replicate()]] (per-arg lists) OR a bare
        # placements list [Shard(0)] for the single-tensor case
        if placements_arg and not isinstance(placements_arg[0],
                                             (list, tuple)):
            return [list(placements_arg)]
        return list(placements_arg)

    def wrapped(*args, **kwargs):
        if in_placements is not None:
            flat = bool(in_placements) and not isinstance(
                in_placements[0], (list, tuple))
            n_tensor_args = sum(isinstance(a, Tensor) for a in args)
            if flat and n_tensor_args > 1:
                raise ValueError(
                    "shard_op: a flat in_placements list like "
                    f"{in_placements!r} is ambiguous for a function "
                    f"receiving {n_tensor_args} tensor arguments — pass "
                    "the nested per-argument form, e.g. "
                    "[[Shard(0)], [Replicate()]] (advisor r5)")
            per_in = _per_item(in_placements)
            if flat:
                # single-tensor case: the flat list means THE tensor
                # argument, wherever it sits — not positionally args[0]
                args = tuple(_place(a, per_in[0])
                             if isinstance(a, Tensor) else a
                             for a in args)
            else:
                args = tuple(
                    _place(a, per_in[i] if i < len(per_in) else None)
                    for i, a in enumerate(args))
        out = op_fn(*args, **kwargs)
        if out_placements is None:
            return out
        per_out = _per_item(out_placements)
        if isinstance(out, (list, tuple)):
            return type(out)(
                _place(o, per_out[i] if i < len(per_out) else None)
                for i, o in enumerate(out))
        return _place(out, per_out[0] if per_out else None)

    return wrapped


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """``dist.shard_layer`` — apply shard_fn(name, layer, mesh) over
    sublayers to place parameters."""
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    else:
        # default: replicate all parameters on the mesh
        for p in layer.parameters():
            sharded = shard_tensor(p, process_mesh,
                                   [Replicate()] * len(process_mesh.shape))
            p.set_data(sharded._data)
    if input_fn is not None or output_fn is not None:
        orig_forward = layer.forward

        def wrapped(*args, **kw):
            if input_fn is not None:
                args = input_fn(args, process_mesh)
            out = orig_forward(*args, **kw)
            if output_fn is not None:
                out = output_fn(out, process_mesh)
            return out
        layer.forward = wrapped
    return layer


class _ShardOptimizer:
    """``dist.shard_optimizer`` wrapper: every accumulator / master
    weight the inner optimizer creates inherits its parameter's sharding
    (or whatever ``shard_fn(acc_name, param, acc)`` returns) — the
    auto-parallel ZeRO entry point (upstream
    python/paddle/distributed/auto_parallel/api.py shard_optimizer,
    UNVERIFIED; reference mount empty)."""

    def __init__(self, optimizer, shard_fn=None):
        self._inner = optimizer
        self._shard_fn = shard_fn
        self._placed: set[int] = set()

    def __getattr__(self, name):
        if name == "_inner":  # deepcopy/pickle probe before __init__
            raise AttributeError(name)
        return getattr(self._inner, name)

    def _place_new_state(self):
        params = {id(p): p for p in self._inner._parameter_list}
        stores = list(self._inner._accumulators.items())
        for acc_name, store in stores:
            for pid, t in store.items():
                if id(t) in self._placed:
                    continue
                p = params.get(pid)
                if p is None:
                    continue
                if self._shard_fn is not None:
                    out = self._shard_fn(acc_name, p, t)
                    if out is not None and out is not t:
                        t.set_data(out._data if isinstance(out, Tensor)
                                   else jax.numpy.asarray(out))
                elif t._data.shape == p._data.shape:
                    t.set_data(jax.device_put(t._data, p._data.sharding))
                self._placed.add(id(t))
        for pid, t in self._inner._master_weights.items():
            if id(t) in self._placed:
                continue
            p = params.get(pid)
            if p is not None and t._data.shape == p._data.shape:
                t.set_data(jax.device_put(t._data, p._data.sharding))
            self._placed.add(id(t))

    def step(self, *a, **k):
        out = self._inner.step(*a, **k)  # LBFGS step(closure) → loss
        self._place_new_state()
        return out

    def minimize(self, loss, *a, **k):
        out = self._inner.minimize(loss, *a, **k)
        self._place_new_state()
        return out

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, state):
        self._inner.set_state_dict(state)
        # restore overwrites existing accumulator tensors in place with
        # replicated host arrays — force a full re-place
        self._placed.clear()
        self._place_new_state()


def shard_optimizer(optimizer, shard_fn=None):
    return _ShardOptimizer(optimizer, shard_fn)
