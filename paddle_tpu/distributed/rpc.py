"""``paddle.distributed.rpc`` — RPC framework parity (upstream
``python/paddle/distributed/rpc/`` over brpc, UNVERIFIED; reference
mount empty).

TPU-native design: the control plane is plain TCP (one listener thread
per worker serving pickled call requests) with rendezvous through the
native ``TCPStore`` (paddle_tpu/native — the same C++ store the
launcher/elastic stack uses). This is host-side coordination machinery:
tensors never ride RPC on TPU (collectives do that); RPC exists for the
reference's control-plane uses — parameter-server-style coordination,
metrics aggregation, custom orchestration.

API parity: ``init_rpc``, ``rpc_sync``, ``rpc_async`` (returns a future
with ``wait()``), ``get_worker_info``, ``get_all_worker_infos``,
``shutdown``.
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import threading
import time
from dataclasses import dataclass

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass(frozen=True)
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


class _Future:
    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._exc = None

    def _set(self, value=None, exc=None):
        self._value, self._exc = value, exc
        self._event.set()

    def wait(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("rpc future timed out")
        if self._exc is not None:
            raise self._exc
        return self._value

    def done(self):
        return self._event.is_set()


class _State:
    def __init__(self):
        self.name = None
        self.rank = None
        self.workers: dict[str, WorkerInfo] = {}
        self.server = None
        self.server_thread = None
        self.store = None
        self.token = None


_state = _State()
_MAGIC = b"PTRPC1"


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("rpc peer closed")
        buf += part
    return buf


def _mac(payload: bytes) -> bytes:
    import hashlib
    import hmac as _hmac

    key = (_state.token or "").encode()
    return _hmac.new(key, payload, hashlib.sha256).digest()


def _send_msg(sock, obj):
    payload = pickle.dumps(obj)
    sock.sendall(_MAGIC + _mac(payload)
                 + len(payload).to_bytes(8, "big") + payload)


def _recv_msg(sock):
    import hmac as _hmac

    head = _recv_exact(sock, len(_MAGIC) + 32 + 8)
    if head[:len(_MAGIC)] != _MAGIC:
        raise ConnectionError("rpc protocol mismatch")
    mac = head[len(_MAGIC):len(_MAGIC) + 32]
    n = int.from_bytes(head[len(_MAGIC) + 32:], "big")
    payload = _recv_exact(sock, n)
    # authenticate BEFORE deserializing: unpickling attacker bytes is
    # itself arbitrary code execution, so the HMAC (keyed by the per-job
    # secret from the rendezvous store) must gate pickle.loads
    if not _hmac.compare_digest(mac, _mac(payload)):
        raise PermissionError("rpc: bad or missing auth token")
    return pickle.loads(payload)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = _recv_msg(self.request)
        except (ConnectionError, PermissionError):
            return
        try:
            fn, args, kwargs = req
            result = fn(*args, **(kwargs or {}))
            reply = ("ok", result)
        except Exception as e:  # noqa: BLE001 — forwarded to the caller
            reply = ("err", e)
        try:
            _send_msg(self.request, reply)
        except Exception:
            # unpicklable result/exception: degrade to a picklable error
            # carrying the repr instead of dropping the connection
            _send_msg(self.request, ("err", RuntimeError(
                f"rpc: reply not picklable: {reply[1]!r}")))


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC server and rendezvous with peers through
    the TCPStore at ``master_endpoint`` (rank 0 hosts the store)."""
    import os

    from ..native import TCPStore

    if _state.server is not None:
        raise RuntimeError("init_rpc already called; shutdown() first")
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else int(rank)
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else int(world_size)
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:29550")
    host, port_s = master_endpoint.rsplit(":", 1)

    # bind only the interface peers will actually dial (loopback when the
    # rendezvous is local) — not 0.0.0.0 — so the pickled-callable
    # listener does not face every interface. Fall back to the wildcard
    # only when the resolved hostname is not locally bindable (NAT'd
    # cloud hosts); the HMAC gate in _recv_msg still authenticates every
    # request before any unpickling.
    my_ip = "127.0.0.1" if host in ("127.0.0.1", "localhost") else \
        socket.gethostbyname(socket.gethostname())

    store = TCPStore(host, int(port_s), is_master=(rank == 0),
                     world_size=world_size)
    # per-job shared secret: rank 0 mints it, everyone reads it from the
    # store; requests are HMAC'd with it and rejected before unpickling
    # (see _recv_msg). The listener only starts AFTER the token exists —
    # no empty-key window.
    import secrets as _secrets
    if rank == 0:
        token = _secrets.token_hex(32)
        store.set("rpc/token", token.encode())
    else:
        token = None
        deadline0 = time.time() + 60
        while not token:
            raw = store.get("rpc/token")
            if raw:
                token = raw.decode()
                break
            if time.time() > deadline0:
                raise TimeoutError("rpc rendezvous: auth token missing")
            time.sleep(0.05)
    _state.token = token

    try:
        server = _Server((my_ip, 0), _Handler)
    except OSError:
        server = _Server(("0.0.0.0", 0), _Handler)
    my_port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    store.set(f"rpc/{rank}",
              pickle.dumps(WorkerInfo(name, rank, my_ip, my_port)))
    workers = {}
    deadline = time.time() + 60
    for r in range(world_size):
        while True:
            raw = store.get(f"rpc/{r}")
            if raw:
                info = pickle.loads(raw)
                workers[info.name] = info
                break
            if time.time() > deadline:
                raise TimeoutError(f"rpc rendezvous: rank {r} missing")
            time.sleep(0.05)

    _state.name, _state.rank = name, rank
    _state.workers = workers
    _state.server, _state.server_thread = server, t
    _state.store = store
    return get_worker_info(name)


def get_worker_info(name=None) -> WorkerInfo:
    if name is None:
        name = _state.name
    try:
        return _state.workers[name]
    except KeyError:
        raise RuntimeError(f"unknown rpc worker {name!r}; "
                           "init_rpc first") from None


def get_all_worker_infos():
    return sorted(_state.workers.values(), key=lambda w: w.rank)


def rpc_async(to, fn, args=None, kwargs=None, timeout=None) -> _Future:
    """Run ``fn(*args, **kwargs)`` on worker ``to``; returns a future.
    ``fn`` must be picklable (module-level) and importable on the
    callee."""
    info = get_worker_info(to)
    fut = _Future()

    def call():
        try:
            with socket.create_connection((info.ip, info.port),
                                          timeout=timeout) as sock:
                _send_msg(sock, (fn, tuple(args or ()),
                                 dict(kwargs or {})))
                status, value = _recv_msg(sock)
            if status == "ok":
                fut._set(value=value)
            else:
                fut._set(exc=value)
        except Exception as e:  # noqa: BLE001
            fut._set(exc=e)

    threading.Thread(target=call, daemon=True).start()
    return fut


def rpc_sync(to, fn, args=None, kwargs=None, timeout=None):
    return rpc_async(to, fn, args=args, kwargs=kwargs,
                     timeout=timeout).wait(timeout)


def shutdown():
    """Barrier with peers, then stop the server (upstream: graceful
    shutdown waits for outstanding work)."""
    st = _state
    if st.server is None:
        return
    if st.store is not None and len(st.workers) > 1:
        done = st.store.add("rpc/shutdown", 1)
        deadline = time.time() + 30
        while done < len(st.workers) and time.time() < deadline:
            time.sleep(0.05)
            done = st.store.add("rpc/shutdown", 0)
        if st.rank == 0:
            # peers poll every 50ms: give them a beat to observe the
            # completed barrier before the master store goes away
            time.sleep(0.5)
    st.server.shutdown()
    st.server.server_close()
    st.__init__()
