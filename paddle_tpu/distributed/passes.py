"""``paddle.distributed.passes`` — the auto-parallel pass registry
(reference: ``python/paddle/distributed/passes``, UNVERIFIED — mount
empty). The reference's distributed passes rewrite the static program
(AMP insertion, recompute insertion, sharding-stage transforms,
gradient-merge); on TPU most of that work is owned by XLA/GSPMD or by
the fleet engines directly, so this registry exposes the same
``new_pass(name, attrs)`` / ``PassManager.apply`` surface while mapping
each known pass either to a real program rewrite (shared with
``paddle.static.passes``) or to a recorded delegated no-op.
"""

from __future__ import annotations

from ..static.passes import (PassManager as _StaticPassManager,
                             register_pass, XLA_DELEGATED_PASSES)

__all__ = ["new_pass", "PassManager", "PassContext",
            "register_pass", "XLA_DELEGATED_PASSES"]

#: distributed pass names the runtime already provides elsewhere:
#: AMP/recompute are config knobs on the model/strategy, sharding
#: stages live in fleet.distributed_optimizer, gradient merge is the
#: pipeline engines' microbatch accumulation, and the fusion passes
#: are XLA's.
_DELEGATED_DISTRIBUTED = frozenset({
    "auto_parallel_amp", "auto_parallel_fp16", "auto_parallel_recompute",
    "auto_parallel_sharding", "auto_parallel_gradient_merge",
    "auto_parallel_data_parallel_optimization",
    "auto_parallel_grad_clip", "auto_parallel_supplement_explicit_dependencies",
    "fuse_all_reduce", "fused_attention", "fused_feedforward",
})


class _Pass:
    def __init__(self, name, attrs=None):
        self.name = name
        self.attrs = dict(attrs or {})

    def apply(self, main_programs, startup_programs=None, context=None):
        if self.attrs:
            # attrs are dropped in BOTH categories (registered rewrites
            # are name-keyed and take no attrs either) — but say which
            # is happening: delegated = the whole pass's work lives
            # elsewhere; registered = the rewrite runs with defaults
            import warnings
            delegated = self.name in _DELEGATED_DISTRIBUTED or \
                self.name in XLA_DELEGATED_PASSES
            if delegated:
                warnings.warn(
                    f"distributed pass {self.name!r}: attrs "
                    f"{sorted(self.attrs)} are recorded but not consumed "
                    "— on this runtime the pass's work is owned by "
                    "XLA/GSPMD, the fleet engines, or model/strategy "
                    "config knobs (configure those directly)",
                    stacklevel=2)
            else:
                warnings.warn(
                    f"distributed pass {self.name!r}: the registered "
                    f"program rewrite runs, but attrs "
                    f"{sorted(self.attrs)} are ignored (rewrites are "
                    "name-keyed and take no attrs)", stacklevel=2)
        mgr = PassManager([self])
        for prog in (main_programs if isinstance(main_programs,
                                                 (list, tuple))
                     else [main_programs]):
            mgr.apply(prog)
        if context is not None:
            context.applied.append(self.name)
        return main_programs


def new_pass(name, pass_attrs=None):
    """Create a named distributed pass (reference
    ``paddle.distributed.passes.new_pass``)."""
    return _Pass(name, pass_attrs)


class PassContext:
    """Carries cross-pass state during application (reference parity;
    here: the applied-pass record)."""

    def __init__(self):
        self.applied: list[str] = []


class PassManager(_StaticPassManager):
    """static.passes.PassManager that additionally accepts the
    distributed delegated pass names and ``_Pass`` objects."""

    def __init__(self, passes=()):
        names = []
        for p in passes:
            names.append(p.name if isinstance(p, _Pass) else p)
        super().__init__(names, extra_delegated=_DELEGATED_DISTRIBUTED)
