"""ZeRO-style sharding — fleet ``DygraphShardingOptimizer`` (stage 1/2) and
``GroupShardedStage3`` parity (UNVERIFIED paths:
fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py,
fleet/meta_parallel/sharding/group_sharded_stage3.py).

TPU-native semantics (SURVEY.md §2.3):
- stage 1/2 = optimizer state (and grads) sharded along the 'sharding' mesh
  axis: accumulators get NamedSharding over their first divisible dim; XLA
  reduce-scatters grads and all-gathers params as needed when the step is
  compiled over the mesh. No hand-written bucketing.
- stage 3 (FSDP) = parameters themselves sharded the same way
  (gather-on-use is XLA's all-gather scheduling).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...framework.core import Tensor
from ...optimizer.optimizer import Optimizer

__all__ = ["DygraphShardingOptimizer", "group_sharded_parallel",
           "GroupShardedStage3", "shard_array_over"]


def _warn_no_offload(where: str) -> None:
    import warnings

    warnings.warn(
        f"{where}(offload=True) is not supported on the TPU backend: "
        "parameters and optimizer state stay in HBM with NamedSharding; "
        "use paddle.incubate.recompute or smaller shards instead. "
        "Proceeding WITHOUT offload.", UserWarning, stacklevel=3)


def shard_array_over(data, mesh, axis_name):
    """NamedSharding over the first dim divisible by the axis size;
    replicate if none."""
    size = mesh.shape[axis_name]
    for d, s in enumerate(data.shape):
        if s % size == 0 and s >= size:
            spec = [None] * data.ndim
            spec[d] = axis_name
            return jax.device_put(data, NamedSharding(mesh,
                                                      PartitionSpec(*spec)))
    return jax.device_put(data, NamedSharding(mesh, PartitionSpec()))


class DygraphShardingOptimizer:
    """Stage-1/2 wrapper: re-places every accumulator (and master weight)
    the inner optimizer creates onto the sharding axis."""

    def __init__(self, optimizer: Optimizer, hcg=None, group=None):
        self._inner = optimizer
        self._hcg = hcg
        if hcg is None:
            from .base import fleet
            self._hcg = fleet._hcg
        self._mesh = self._hcg.global_mesh if self._hcg else None
        self._axis = self._hcg.sharding_axis_name if self._hcg else None
        self._placed: set[int] = set()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def _parameter_list(self):
        return self._inner._parameter_list

    def _place_new_state(self):
        if self._mesh is None:
            return
        for store in self._inner._accumulators.values():
            for t in store.values():
                if id(t) not in self._placed and t._data.ndim > 0:
                    t.set_data(shard_array_over(t._data, self._mesh,
                                                self._axis))
                    self._placed.add(id(t))
        for t in self._inner._master_weights.values():
            if id(t) not in self._placed:
                t.set_data(shard_array_over(t._data, self._mesh,
                                            self._axis))
                self._placed.add(id(t))

    def step(self):
        self._inner.step()
        self._place_new_state()

    def minimize(self, loss, *a, **k):
        out = self._inner.minimize(loss, *a, **k)
        self._place_new_state()
        return out

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, state):
        self._inner.set_state_dict(state)
        self._place_new_state()


class GroupShardedStage3:
    """Stage-3 (FSDP) wrapper: parameters sharded over the sharding axis;
    XLA all-gathers on use and reduce-scatters grads when the train step is
    compiled over the mesh."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, offload=False, hcg=None):
        if offload:
            _warn_no_offload("GroupShardedStage3")
        # segment_size is accepted for API parity but has no effect: XLA
        # schedules all-gathers itself, there is no manual bucketing.
        self._layer = layer
        self._optimizer = optimizer
        if hcg is None:
            from .base import fleet
            hcg = fleet._hcg
        self._hcg = hcg
        mesh = hcg.global_mesh if hcg else None
        axis = hcg.sharding_axis_name if hcg else None
        if mesh is not None:
            for p in layer.parameters():
                p.set_data(shard_array_over(p._data, mesh, axis))
        if optimizer is not None and mesh is not None:
            # shard any existing accumulators too
            DygraphShardingOptimizer(optimizer, hcg)._place_new_state()

    def __getattr__(self, name):
        return getattr(self._layer, name)

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def parameters(self, *a, **k):
        return self._layer.parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layer.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layer.set_state_dict(*a, **k)


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """``paddle.distributed.sharding.group_sharded_parallel`` parity.
    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3)."""
    from .base import fleet
    hcg = fleet._hcg
    if offload and level in ("os", "os_g"):
        _warn_no_offload("group_sharded_parallel")
    if level in ("os", "os_g"):
        opt = DygraphShardingOptimizer(optimizer, hcg)
        opt._place_new_state()
        return model, opt, scaler
    model = GroupShardedStage3(model, optimizer, group=group,
                               offload=offload, hcg=hcg)
    opt = DygraphShardingOptimizer(optimizer, hcg)
    return model, opt, scaler
