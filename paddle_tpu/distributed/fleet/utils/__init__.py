"""fleet.utils — recompute + sequence-parallel helpers
(fleet/utils/ parity, UNVERIFIED)."""

from ....incubate.recompute import recompute
from . import sequence_parallel_utils
from .fs import (LocalFS, HDFSClient, FSFileExistsError,
                 FSFileNotExistsError)
from .sequence_parallel_utils import (
    ScatterOp, GatherOp, AllGatherOp, ReduceScatterOp,
    ColumnSequenceParallelLinear, RowSequenceParallelLinear,
    mark_as_sequence_parallel_parameter,
    register_sequence_parallel_allreduce_hooks)

__all__ = ["recompute", "sequence_parallel_utils", "LocalFS",
           "HDFSClient", "FSFileExistsError", "FSFileNotExistsError",
           "ScatterOp", "GatherOp",
           "AllGatherOp", "ReduceScatterOp", "ColumnSequenceParallelLinear",
           "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]
