"""``fleet.utils.fs`` — filesystem clients for checkpoint/data staging
(upstream python/paddle/distributed/fleet/utils/fs.py, UNVERIFIED;
reference mount empty).

``LocalFS`` is fully functional. ``HDFSClient`` keeps the API surface
but needs a hadoop client binary, which the TPU image does not ship —
constructing one raises with that explanation (the PS-era HDFS data
path is out of TPU scope; see PARITY.md)."""

from __future__ import annotations

import os
import shutil

from ....utils.retry import retry_call

__all__ = ["LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class LocalFS:
    """Local filesystem with the upstream FS client API. Data-moving
    operations retry transient I/O errors (EIO/EAGAIN/ENOSPC...) with
    bounded exponential backoff — checkpoint staging over a flaky
    mount should not die on a single blip."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        retry_call(os.makedirs, fs_path, exist_ok=True)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        def _touch():
            with open(fs_path, "a"):
                pass
        retry_call(_touch)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        else:
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(dst_path)
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def upload(self, local_path, fs_path):
        retry_call(shutil.copy, local_path, fs_path)

    def download(self, fs_path, local_path):
        retry_call(shutil.copy, fs_path, local_path)

    def cat(self, fs_path=None):
        def _read():
            with open(fs_path, "rb") as fh:
                return fh.read()
        return retry_call(_read)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """Unsupported on TPU: construction always raises. The filesystem
    methods are not implemented here, so succeeding past __init__ on a
    hadoop-equipped host would only defer the failure to the first
    method call — raise up front with the explanation instead."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60,
                 sleep_inter=1000):
        raise RuntimeError(
            "HDFSClient is not supported in the TPU build — the PS-era "
            "HDFS data path is out of TPU scope (PARITY.md). Use "
            "LocalFS or a mounted filesystem instead.")
