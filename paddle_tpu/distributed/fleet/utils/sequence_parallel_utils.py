"""Megatron-style sequence parallelism — parity with fleet
``utils/sequence_parallel_utils.py`` (ScatterOp/GatherOp/AllGatherOp/
ReduceScatterOp autograd-aware comm ops + Column/RowSequenceParallelLinear
+ mark_as_sequence_parallel_parameter; SURVEY.md §2.3 SP row. Reference
mount empty, no cites).

TPU-native mechanism: in the reference, SP hand-writes the comm pattern —
activations around LayerNorm/dropout are *scattered* along the sequence
dim within the TP group (memory win), and the Column/Row linears trade the
TP identity/allreduce pair for allgather/reduce-scatter. Under GSPMD all
four ops are *sharding constraints* on the seq dim over the 'model' mesh
axis: XLA inserts exactly those allgathers/reduce-scatters, placed and
overlapped by the scheduler. Inside an explicit shard_map region the ops
lower to the literal collectives, matching the reference semantics.

The parameter-marking / hook-registration APIs exist for source parity:
with GSPMD the LayerNorm params are replicated and their grads are
correctly summed by the partitioner, so the hooks are no-ops.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ....framework.core import Tensor, apply
from ....nn.layer.layers import Layer
from ....nn import functional as F
from ....nn import initializer as I
from ...communication import axis_in_traced_region

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]


def _mp():
    from ..base import fleet as fleet_singleton
    hcg = fleet_singleton._hcg
    if hcg is None:
        return None, None, 1
    return (hcg.mp_axis_name, hcg.global_mesh,
            hcg.get_model_parallel_world_size())


def _constrain(t: Tensor, spec) -> Tensor:
    axis, mesh, world = _mp()
    if mesh is None or world <= 1:
        return t
    from ...parallel_layers import _constrain_tensor
    return _constrain_tensor(t, mesh, spec, name="sp_constraint")


def ScatterOp(x, axis=1):
    """Split activations along the sequence dim across the TP group.
    GSPMD: a seq-dim sharding constraint. shard_map: reduce_scatter-free
    local slice (inputs are replicated in the mp group there)."""
    axis_name, mesh, world = _mp()
    if world <= 1:
        return x
    if axis_in_traced_region(axis_name):
        def fn(a):
            r = lax.axis_index(axis_name)
            per = a.shape[axis] // lax.axis_size(axis_name)
            return lax.dynamic_slice_in_dim(a, r * per, per, axis)
        return apply(fn, x, name="sp_scatter")
    spec = [None] * x.ndim
    spec[axis] = axis_name
    return _constrain(x, PartitionSpec(*spec))


def GatherOp(x, axis=1):
    """Re-assemble the full sequence (inverse of ScatterOp)."""
    axis_name, mesh, world = _mp()
    if world <= 1:
        return x
    if axis_in_traced_region(axis_name):
        return apply(lambda a: lax.all_gather(a, axis_name, axis=axis,
                                              tiled=True), x,
                     name="sp_gather")
    return _constrain(x, PartitionSpec(*([None] * x.ndim)))


# reference aliases: AllGather on the seq dim / ReduceScatter of partials
AllGatherOp = GatherOp


def ReduceScatterOp(x, axis=1):
    """Sum partial activations over the TP group and shard the result
    along the seq dim (row-parallel epilogue under SP)."""
    axis_name, mesh, world = _mp()
    if world <= 1:
        return x
    if axis_in_traced_region(axis_name):
        return apply(lambda a: lax.psum_scatter(a, axis_name,
                                                scatter_dimension=axis,
                                                tiled=True), x,
                     name="sp_reduce_scatter")
    # GSPMD: a psum has already been folded by the partitioner; constrain
    # the result onto the seq dim
    spec = [None] * x.ndim
    spec[axis] = axis_name
    return _constrain(x, PartitionSpec(*spec))


class ColumnSequenceParallelLinear(Layer):
    """Column-parallel linear whose INPUT is sequence-sharded: the seq dim
    is gathered (by GSPMD/collective) and the output is feature-sharded."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, mp_group=None,
                 name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        axis, mesh, world = _mp()
        self._axis, self._mesh, self.world_size = axis, mesh, world
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = world > 1
        if mesh is not None and world > 1:
            self.weight.set_data(jax.device_put(
                self.weight._data,
                NamedSharding(mesh, PartitionSpec(None, axis))))
        self.bias = self.create_parameter(
            [out_features], is_bias=True,
            default_initializer=I.Constant(0.0)) if has_bias else None

    def forward(self, x):
        axis, world = self._axis, self.world_size
        if axis_in_traced_region(axis) and world > 1:
            x = GatherOp(x, axis=1)
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output and self._mesh is not None and world > 1 \
                and not axis_in_traced_region(axis):
            spec = [None] * out.ndim
            spec[-1] = axis
            out = _constrain(out, PartitionSpec(*spec))
        return out


class RowSequenceParallelLinear(Layer):
    """Row-parallel linear whose OUTPUT is sequence-sharded: partial sums
    are reduce-scattered along the seq dim instead of allreduced."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, mp_group=None,
                 name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        axis, mesh, world = _mp()
        self._axis, self._mesh, self.world_size = axis, mesh, world
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = world > 1
        if mesh is not None and world > 1:
            self.weight.set_data(jax.device_put(
                self.weight._data,
                NamedSharding(mesh, PartitionSpec(axis, None))))
        self.bias = self.create_parameter(
            [out_features], is_bias=True,
            default_initializer=I.Constant(0.0)) if has_bias else None

    def forward(self, x):
        axis, world = self._axis, self.world_size
        if axis_in_traced_region(axis) and world > 1:
            out = F.linear(x, self.weight, None)
            out = ReduceScatterOp(out, axis=1)
            if self.bias is not None:
                out = out + self.bias
            return out
        out = F.linear(x, self.weight, None)
        if self._mesh is not None and world > 1:
            out = ReduceScatterOp(out, axis=1)
        if self.bias is not None:
            out = out + self.bias
        return out


def mark_as_sequence_parallel_parameter(param):
    """Reference: tags LayerNorm params in the SP region so their grads
    get allreduced over the TP group. GSPMD sums replicated-param grads
    automatically; we keep the tag for introspection/source parity."""
    param.sequence_parallel = True
    return param


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """No-op under GSPMD (see module docstring); kept for source parity."""
    return model
