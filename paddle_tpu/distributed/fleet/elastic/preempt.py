"""Preemption handling — the worker-side half of elastic fault
tolerance.

A production TPU fleet preempts workers as a matter of course; the
difference between a preemption and a crash is the *grace window*: the
scheduler sends SIGTERM and gives the process a bounded number of
seconds before SIGKILL. The contract here:

- :class:`PreemptionGuard` turns the asynchronous signal into a flag a
  training loop polls at step boundaries — the signal handler does
  nothing but record the time (async-signal-safe); the hot loop keeps
  its compiled-step cadence and drains cleanly at the next boundary.
- The loop then writes a bounded-time **emergency checkpoint** (the
  commit barrier gets the *remaining grace*, not the default 300 s —
  an uncommitted save at SIGKILL is the safe outcome, a blocked one is
  not) and raises :class:`Preempted`.
- The trainer exits with :data:`PREEMPTED_EXIT_CODE` (``os.EX_TEMPFAIL``
  = 75, "temporary failure, retry"), which the elastic launcher
  classifies as a *clean preemption* — relaunch on its own budget —
  instead of a crash that burns the restart budget.

A second/third SIGTERM while draining escalates: the third forces
immediate exit (the operator means it)."""

from __future__ import annotations

import os
import signal
import threading
import time

__all__ = ["PreemptionGuard", "Preempted", "PREEMPTED_EXIT_CODE"]

#: worker exit code for a clean preemption (emergency checkpoint
#: committed, state resumable): os.EX_TEMPFAIL = 75 — "temporary
#: failure, retry", which is exactly the launcher's contract
PREEMPTED_EXIT_CODE = getattr(os, "EX_TEMPFAIL", 75)

#: grace window the preemptor allows between SIGTERM and SIGKILL
_GRACE_ENV = "PADDLE_PREEMPT_GRACE_S"
_DEFAULT_GRACE_S = 30.0

#: signals escalate: 3rd SIGTERM while draining -> immediate exit
_FORCE_AFTER = 3


class Preempted(RuntimeError):
    """Raised by a preemption-aware training loop AFTER the emergency
    checkpoint committed — carries what the relaunch needs to know.
    Trainers normally let it propagate and exit with
    ``PREEMPTED_EXIT_CODE`` (see ``exit_code``)."""

    def __init__(self, message, checkpoint=None, epoch=None, step=None):
        super().__init__(message)
        self.checkpoint = checkpoint
        self.epoch = epoch
        self.step = step
        self.exit_code = PREEMPTED_EXIT_CODE


def _install_excepthook():
    """Make the documented contract true without trainer boilerplate:
    an UNCAUGHT :class:`Preempted` exits the process with
    ``PREEMPTED_EXIT_CODE`` (not the generic 1 that the launcher would
    book as a crash). Chained once, process-wide; trainers that catch
    Preempted themselves are unaffected."""
    import sys
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        if isinstance(exc, Preempted):
            # drop a flight-recorder bundle first (ring + thread
            # stacks + metrics snapshot): the preemption becomes a
            # diagnosable artifact, not just an exit code
            try:
                from ....profiler import flight_recorder as _frec
                rec = _frec.get_recorder()
                if rec is not None:
                    rec.dump(f"preempted: {exc}")
            except Exception:  # noqa: BLE001 — the exit must proceed
                pass
            print(f"paddle_tpu: {exc} — exiting "
                  f"{exc.exit_code} (clean preemption)", file=sys.stderr)
            sys.exit(exc.exit_code)
        prev(exc_type, exc, tb)

    hook._paddle_preempt = True  # idempotence marker
    if not getattr(prev, "_paddle_preempt", False):
        sys.excepthook = hook


class PreemptionGuard:
    """SIGTERM → pollable flag with a grace-window deadline.

    ``install()`` claims the signal handler (main thread only — from a
    worker thread the guard stays inert and ``requested()`` can still
    be driven via :meth:`request`, the test/manual hook) and chains a
    ``sys.excepthook`` so an uncaught :class:`Preempted` exits with
    ``PREEMPTED_EXIT_CODE`` instead of reading as a crash.
    ``uninstall()`` restores the previous signal handler; use as a
    context manager in loops that must not leak the handler."""

    def __init__(self, signals=(signal.SIGTERM,), grace_s=None):
        self.signals = tuple(signals)
        if grace_s is None:
            grace_s = float(os.environ.get(_GRACE_ENV, _DEFAULT_GRACE_S))
        self.grace_s = float(grace_s)
        self._requested_at = None
        self._count = 0
        self._prev = {}
        self._installed = False
        self._lock = threading.Lock()

    # -- signal side (async-safe: record + count only) ---------------------

    def _on_signal(self, signum, frame):
        self._count += 1
        if self._requested_at is None:
            self._requested_at = time.time()
        if self._count >= _FORCE_AFTER:
            # repeated signals mean "now": skip python unwinding
            os._exit(128 + int(signum))

    def request(self, grace_s=None):
        """Mark preemption as requested without a real signal — the
        deterministic hook for tests and cooperative schedulers that
        deliver preemption notices in-band (a queue message, a
        metadata-server poll) rather than via SIGTERM."""
        if grace_s is not None:
            self.grace_s = float(grace_s)
        if self._requested_at is None:
            self._requested_at = time.time()
        self._count += 1
        return self

    # -- loop side ---------------------------------------------------------

    def requested(self) -> bool:
        """Poll at step boundaries: has a preemption been signalled?"""
        return self._requested_at is not None

    def remaining(self) -> float:
        """Seconds left in the grace window (``inf`` before any
        signal, floored at 1 s after — the emergency save always gets
        a nonzero bound to attempt its commit in)."""
        if self._requested_at is None:
            return float("inf")
        return max(1.0, self._requested_at + self.grace_s - time.time())

    def reset(self):
        self._requested_at = None
        self._count = 0
        return self

    # -- handler lifecycle -------------------------------------------------

    def install(self):
        with self._lock:
            if self._installed:
                return self
            try:
                for sig in self.signals:
                    self._prev[sig] = signal.signal(sig, self._on_signal)
                self._installed = True
            except ValueError:
                # not the main thread: signals cannot be claimed here;
                # the guard still works through request()
                self._prev.clear()
            _install_excepthook()
        return self

    def uninstall(self):
        with self._lock:
            if not self._installed:
                return
            for sig, prev in self._prev.items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, TypeError):
                    pass
            self._prev.clear()
            self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
