"""Elastic training — fleet ``elastic/manager.py`` parity (UNVERIFIED;
reference mount empty).

Reference design (SURVEY.md §5 "Failure detection / elastic"): etcd node
registry + heartbeats; on peer loss the launch controller tears down
local trainers and re-launches; recovery is checkpoint-restart, not
in-process resume.

TPU-native: the registry is the framework's own ``TCPStore`` control
plane (paddle_tpu.native — the same store that does rendezvous), or a
shared-filesystem heartbeat directory when no store is reachable (the
single-host / tests path). Worker processes run a daemon heartbeat
thread; the launcher (or any watcher) polls for stale peers and drives
SIGTERM → relaunch. Recovery stays checkpoint-restart: see
``latest_checkpoint`` / ``checkpoint_step`` helpers.
"""

from .manager import (ElasticManager, ElasticStatus, start_heartbeat,
                      stop_heartbeat, latest_checkpoint, checkpoint_step,
                      latest_valid_checkpoint)
from .preempt import PreemptionGuard, Preempted, PREEMPTED_EXIT_CODE

__all__ = ["ElasticManager", "ElasticStatus", "start_heartbeat",
           "stop_heartbeat", "latest_checkpoint", "checkpoint_step",
           "latest_valid_checkpoint", "PreemptionGuard", "Preempted",
           "PREEMPTED_EXIT_CODE"]
