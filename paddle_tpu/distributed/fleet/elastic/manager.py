"""Elastic manager: heartbeat registry + fault watch + checkpoint-restart
helpers (fleet ``elastic/manager.py`` role; reference mount empty, no
file:line cites).

Two registry backends behind one API:

- **store**: a ``TCPStore`` (host:port) — each worker ``set``s its
  heartbeat key every interval; the watcher reads all keys and flags
  ranks whose timestamp went stale. Multi-host path (the role etcd
  plays in the reference).
- **dir**: a shared directory — each worker touches
  ``heartbeat.{rank}``; the watcher checks mtimes. Single-host /
  CI path (and the natural fit for the launcher's per-node watchdog).
"""

from __future__ import annotations

import enum
import os
import threading
import time

__all__ = ["ElasticManager", "ElasticStatus", "start_heartbeat",
           "stop_heartbeat", "latest_checkpoint", "checkpoint_step",
           "latest_valid_checkpoint"]


class ElasticStatus(enum.Enum):
    HEALTHY = 0
    STALE = 1       # some rank missed its heartbeat window
    INCOMPLETE = 2  # not all ranks have registered yet


# --------------------------------------------------------------------------
# worker side: heartbeat thread
# --------------------------------------------------------------------------

_worker = {"thread": None, "stop": None}


def _beat_once(rank, directory=None, store=None):
    now = str(time.time()).encode()
    if directory is not None:
        path = os.path.join(directory, f"heartbeat.{rank}")
        with open(path, "w") as f:
            f.write(now.decode())
    if store is not None:
        store.set(f"elastic/beat/{rank}", now)


def start_heartbeat(rank=None, directory=None, store=None, interval=1.0):
    """Start the daemon heartbeat thread for this worker process.

    directory and/or store select the registry backend(s). When rank or
    directory is None they default from the launcher-set env
    (``PADDLE_ELASTIC_HEARTBEAT_RANK`` / ``_DIR``) — note the rank key
    is the *node-local* rank: each node's launcher watches only its own
    workers, so a training script can call ``start_heartbeat()`` with
    no arguments under any topology."""
    if rank is None:
        rank = int(os.environ.get("PADDLE_ELASTIC_HEARTBEAT_RANK",
                                  os.environ.get("PADDLE_LOCAL_RANK",
                                                 "0")))
    if directory is None:
        directory = os.environ.get("PADDLE_ELASTIC_HEARTBEAT_DIR")
    if directory is None and store is None:
        return False
    stop_heartbeat()  # one heartbeat thread per process
    if directory is not None:
        os.makedirs(directory, exist_ok=True)
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            try:
                _beat_once(rank, directory, store)
            except Exception:
                pass  # registry hiccups must never kill the trainer
            stop.wait(interval)

    _beat_once(rank, directory, store)
    t = threading.Thread(target=loop, name="elastic-heartbeat",
                         daemon=True)
    t.start()
    _worker["thread"], _worker["stop"] = t, stop
    return True


def stop_heartbeat():
    if _worker["stop"] is not None:
        _worker["stop"].set()
        _worker["thread"].join(timeout=2.0)
        _worker["thread"] = _worker["stop"] = None


# --------------------------------------------------------------------------
# watcher side
# --------------------------------------------------------------------------

class ElasticManager:
    """Fault watcher over the heartbeat registry.

    watch() returns an ElasticStatus; the caller (launcher) decides the
    response — the reference semantics: kill local trainers and
    re-launch from the latest checkpoint."""

    def __init__(self, world_size, directory=None, store=None,
                 timeout=10.0):
        if directory is None and store is None:
            raise ValueError("ElasticManager needs a directory or store")
        self.world_size = int(world_size)
        self.directory = directory
        self.store = store
        self.timeout = float(timeout)

    def _beats(self):
        beats = {}
        if self.directory is not None:
            for r in range(self.world_size):
                p = os.path.join(self.directory, f"heartbeat.{r}")
                try:
                    beats[r] = os.path.getmtime(p)
                except OSError:
                    pass
        if self.store is not None:
            for r in range(self.world_size):
                v = self.store.get(f"elastic/beat/{r}")
                if v:
                    beats[r] = max(beats.get(r, 0.0), float(v))
        return beats

    def watch(self, ignore=()):
        """One poll: (status, stale_ranks). ``ignore``: ranks exempt
        from staleness (e.g. workers that already exited cleanly)."""
        beats = self._beats()
        watched = [r for r in range(self.world_size) if r not in ignore]
        missing = [r for r in watched if r not in beats]
        if missing:
            return ElasticStatus.INCOMPLETE, missing
        now = time.time()
        stale = [r for r in watched
                 if now - beats[r] > self.timeout]
        if stale:
            # a stale heartbeat is the elastic no-progress signal: drop
            # a flight-recorder bundle from the watcher process (ring +
            # stacks + metrics) before the launcher tears the round down
            from ....profiler import flight_recorder as _frec
            _frec.record_event("heartbeat_stale", ranks=list(stale),
                               gap_s=round(now - min(
                                   beats[r] for r in stale), 3))
            rec = _frec.get_recorder()
            if rec is not None:
                try:
                    rec.dump(f"elastic heartbeat gap: ranks {stale} "
                             f"stale past {self.timeout}s")
                except OSError:
                    pass    # the launcher must still receive STALE and
                            # tear the round down; the bundle is a bonus
            return ElasticStatus.STALE, stale
        return ElasticStatus.HEALTHY, []

    def wait_all_registered(self, timeout=60.0, poll=0.2):
        end = time.time() + timeout
        while time.time() < end:
            status, _ = self.watch()
            if status is not ElasticStatus.INCOMPLETE:
                return True
            time.sleep(poll)
        return False

    def reset(self):
        """Clear registered beats (before a relaunch round)."""
        if self.directory is not None:
            for r in range(self.world_size):
                p = os.path.join(self.directory, f"heartbeat.{r}")
                try:
                    os.remove(p)
                except OSError:
                    pass
        if self.store is not None:
            for r in range(self.world_size):
                try:
                    self.store.delete_key(f"elastic/beat/{r}")
                except Exception:
                    pass


# --------------------------------------------------------------------------
# checkpoint-restart helpers
# --------------------------------------------------------------------------

def checkpoint_step(path):
    """Step number encoded in a ``step_N`` checkpoint dir name, else -1."""
    base = os.path.basename(os.path.normpath(path))
    if base.startswith("step_"):
        try:
            return int(base[len("step_"):])
        except ValueError:
            pass
    return -1


def latest_checkpoint(root):
    """Newest ``step_N`` subdirectory of root by name only, or None.
    Ignores in-progress staging dirs (``.tmp`` / ``.tmp-<uid>``). Does
    NOT check the checkpoint is loadable — restart paths should prefer
    :func:`latest_valid_checkpoint`, which skips torn saves."""
    if not os.path.isdir(root):
        return None
    best, best_step = None, -1
    for name in os.listdir(root):
        full = os.path.join(root, name)
        if not os.path.isdir(full) or ".tmp" in name:
            continue
        s = checkpoint_step(full)
        if s > best_step:
            best, best_step = full, s
    return best


def latest_valid_checkpoint(root, deep=False):
    """Newest *committed* ``step_N`` checkpoint under root — validated
    against the atomic-commit protocol (``COMMITTED`` sentinel +
    metadata checksums), skipping torn/in-progress/corrupt saves, so a
    relaunch always resumes from the last good step. Delegates to
    ``distributed.checkpoint.validation`` — the jax-free half of the
    checkpoint layer, so the launcher-side watcher validates
    checkpoints without touching device state."""
    from ...checkpoint.validation import \
        latest_valid_checkpoint as _latest_valid
    return _latest_valid(root, deep=deep)
