"""``fleet.meta_optimizers`` package path parity (reference:
``python/paddle/distributed/fleet/meta_optimizers/``, UNVERIFIED —
mount empty). The actual optimizers live in ``fleet.sharding`` /
``fleet.hybrid_optimizer``; this package re-exports them under the
reference import paths."""

from ..sharding import DygraphShardingOptimizer
from ..hybrid_optimizer import HybridParallelOptimizer

__all__ = ["DygraphShardingOptimizer", "HybridParallelOptimizer"]
