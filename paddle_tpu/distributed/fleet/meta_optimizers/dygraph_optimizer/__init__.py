"""Reference path ``fleet.meta_optimizers.dygraph_optimizer`` — the
dygraph sharding/hybrid optimizers under their upstream import path."""

from ...sharding import DygraphShardingOptimizer
from ...hybrid_optimizer import HybridParallelOptimizer

__all__ = ["DygraphShardingOptimizer", "HybridParallelOptimizer"]
