"""fleet.meta_parallel — parallel layer wrappers + pipeline engine
(fleet/meta_parallel/ parity, UNVERIFIED)."""

from ...parallel_layers import (ColumnParallelLinear, RowParallelLinear,
                                VocabParallelEmbedding, ParallelCrossEntropy)
from .pp_layers import (LayerDesc, SharedLayerDesc,
                        LocalSharedLayerDesc, PipelineLayer)
from .pipeline_parallel import PipelineParallel
from .context_parallel import (RingFlashAttention, ring_flash_attention,
                               ulysses_attention,
                               split_inputs_sequence_dim,
                               gather_outputs_sequence_dim, sep_positions)
from ....framework.random import get_rng_state_tracker

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy", "LayerDesc",
           "SharedLayerDesc", "PipelineLayer", "PipelineParallel",
           "RingFlashAttention", "ring_flash_attention", "ulysses_attention",
           "split_inputs_sequence_dim", "gather_outputs_sequence_dim",
           "sep_positions", "get_rng_state_tracker", "TensorParallel"]


def TensorParallel(model, hcg=None, **kwargs):
    """Wrapper parity: TP layers already carry shardings; returns model."""
    return model
