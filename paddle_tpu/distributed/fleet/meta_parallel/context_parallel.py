"""SEP / context-parallel fleet surface — parity with the reference 'sep'
hybrid-topology axis + PaddleNLP ``ring_flash_attention.py`` (SURVEY.md
§2.3: CP/ring attention + Ulysses rows; reference mount empty, paths
unverified).

Tensor-level wrappers over the pure-jax core in
``paddle_tpu.ops.ring_attention``: inside a compiled region whose mesh
binds the 'sep' axis these lower to the ppermute K/V ring (or all-to-all
for Ulysses); with sep degree 1 they fall back to ordinary full attention
so the same model code runs everywhere (loss-parity oracle)."""

from __future__ import annotations

import jax.numpy as jnp

from ....framework.core import Tensor, apply
from ....ops import ring_attention as ra
from ....nn.functional.attention import sdpa_reference
from ...communication import in_traced_collective

__all__ = ["RingFlashAttention", "ring_flash_attention", "ulysses_attention",
           "sep_attention", "sep_attention_manual", "sep_axis_is_manual",
           "split_inputs_sequence_dim",
           "gather_outputs_sequence_dim", "sep_positions"]


def _hcg():
    from ..base import fleet as fleet_singleton
    return fleet_singleton._hcg


def _sep_axis():
    hcg = _hcg()
    if hcg is None:
        return None, 1
    return hcg.sep_axis_name, hcg.get_sep_parallel_world_size()


def ring_flash_attention(q, k, v, causal=True, scale=None,
                         placement="contiguous"):
    """Exact attention over a 'sep'-sharded sequence via the K/V ring.
    q/k/v: Tensors [B, S_local, H, D]. Falls back to full attention when
    the sep axis is unbound (sep degree 1)."""
    axis, degree = _sep_axis()
    group = _hcg().get_sep_parallel_group() if _hcg() is not None else None
    if axis is not None and in_traced_collective(group):
        def fn(qq, kk, vv):
            return ra.ring_attention(qq, kk, vv, axis, causal=causal,
                                     scale=scale, placement=placement)
        return apply(fn, q, k, v, name="ring_flash_attention")

    def fn(qq, kk, vv):
        return sdpa_reference(qq, kk, vv, None, 0.0, causal, scale)
    return apply(fn, q, k, v, name="ring_flash_attention_local")


# PaddleNLP class-style alias
RingFlashAttention = ring_flash_attention


def ulysses_attention(q, k, v, causal=True, scale=None):
    """Ulysses SEP attention (all-to-all head<->seq reshuffle)."""
    axis, degree = _sep_axis()
    group = _hcg().get_sep_parallel_group() if _hcg() is not None else None
    if axis is not None and in_traced_collective(group):
        def fn(qq, kk, vv):
            return ra.ulysses_attention(qq, kk, vv, axis, causal=causal,
                                        scale=scale)
        return apply(fn, q, k, v, name="ulysses_attention")

    def fn(qq, kk, vv):
        return sdpa_reference(qq, kk, vv, None, 0.0, causal, scale)
    return apply(fn, q, k, v, name="ulysses_attention_local")


def sep_attention(q, k, v, causal=True, scale=None, impl="ring",
                  placement="contiguous"):
    """Context-parallel attention for *global-view* (GSPMD) programs.

    Takes full-sequence Tensors [B, S, H, D] inside a jitted train step and
    runs the K/V ring (or Ulysses all-to-all) manually over the mesh's
    'sep' axis only — every other axis (data, model, sharding) stays under
    automatic GSPMD partitioning (``jax.shard_map(axis_names={'sep'})``).
    This is how the sep engine composes with TP/DP in one compiled program.
    Falls back to full attention at sep degree 1."""
    import jax as _jax
    from jax.sharding import PartitionSpec as P

    hcg = _hcg()
    axis, degree = _sep_axis()
    if hcg is None or degree <= 1 or hcg.global_mesh is None:
        def fb(qq, kk, vv):
            return sdpa_reference(qq, kk, vv, None, 0.0, causal, scale)
        return apply(fb, q, k, v, name="sep_attention_local")

    mesh = hcg.global_mesh
    spec = P(None, axis, None, None)
    if impl == "ring":
        core = lambda a, b, c: ra.ring_attention(
            a, b, c, axis, causal=causal, scale=scale, placement=placement)
    elif impl == "ulysses":
        core = lambda a, b, c: ra.ulysses_attention(
            a, b, c, axis, causal=causal, scale=scale)
    elif impl == "allgather":
        core = lambda a, b, c: ra.allgather_attention(
            a, b, c, axis, causal=causal, scale=scale)
    else:
        raise ValueError(f"unknown sep impl {impl!r}")
    if impl != "ring" and placement != "contiguous":
        # zigzag is the ring's causal load-balancing layout; the other
        # impls assume contiguous global positions — silently wrong
        # masking otherwise
        raise ValueError(
            f"placement={placement!r} is only supported with "
            f"impl='ring' (got impl={impl!r})")

    def fn(qq, kk, vv):
        from ....utils.jax_compat import shard_map as _shard_map
        f = _shard_map(core, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, axis_names={axis})
        return f(qq, kk, vv)

    return apply(fn, q, k, v, name=f"sep_attention_{impl}")


def sep_axis_is_manual() -> bool:
    """True when the 'sep' mesh axis is already MANUALLY bound in the
    current trace — i.e. we are inside a shard_map region that includes
    'sep' in its axis_names (the compiled pipeline engine running a 5D
    pp x sep hybrid). Attention layers branch on this: in a manual
    region the K/V ring is issued directly on the bound axis with
    globally-offset RoPE, instead of opening a (GSPMD-composed)
    partial-manual shard_map of their own."""
    from ...communication import axis_in_traced_region
    axis, degree = _sep_axis()
    return axis is not None and degree > 1 and axis_in_traced_region(axis)


def sep_attention_manual(q, k, v, rope_theta, causal=True,
                         scale=None, impl="ring"):
    """Context-parallel attention for MANUAL regions (the 5D hybrid).

    Called on *pre-RoPE* local chunks [B, S_local, H, D] inside a
    shard_map whose axis_names include BOTH 'pipe' and 'sep' (the
    compiled pipeline engine, ``distributed/pipeline.py``). The sequence
    dim is physically local here, so RoPE must use global token
    positions: this wrapper computes ``idx*S_local + arange(S_local)``
    from ``lax.axis_index('sep')``, applies RoPE to q/k, then runs the
    K/V ring (or Ulysses all-to-all) directly on the already-bound axis
    — ring-CP activations thereby cross pipeline-stage boundaries inside
    ONE compiled program.

    Why rope lives in here and not in the model: the offset is only
    known from the bound axis index; in the GSPMD path the model applies
    rope itself on the full logical sequence."""
    from jax import lax

    axis, degree = _sep_axis()

    def fn(qq, kk, vv):
        from ....ops.pallas import rope as rope_mod
        idx = lax.axis_index(axis)
        sl = qq.shape[1]
        pid = (idx.astype(jnp.int32) * sl
               + jnp.arange(sl, dtype=jnp.int32))[None, :]
        pid = jnp.broadcast_to(pid, (qq.shape[0], sl))
        # table length = the static GLOBAL sequence length (degree
        # local chunks), matching the GSPMD path's build_sin_cos(S_full)
        # exactly — never clamp positions to max_position_embeddings
        s_tab, c_tab = rope_mod.build_sin_cos(degree * sl, qq.shape[-1],
                                              rope_theta, qq.dtype)
        qq = rope_mod.apply_rope(qq, s_tab, c_tab, pid)
        kk = rope_mod.apply_rope(kk, s_tab, c_tab, pid)
        if impl == "ring":
            return ra.ring_attention(qq, kk, vv, axis, causal=causal,
                                     scale=scale, placement="contiguous")
        if impl == "ulysses":
            return ra.ulysses_attention(qq, kk, vv, axis, causal=causal,
                                        scale=scale)
        if impl == "allgather":
            return ra.allgather_attention(qq, kk, vv, axis, causal=causal,
                                          scale=scale)
        raise ValueError(f"unknown sep impl {impl!r}")

    return apply(fn, q, k, v, name=f"sep_attention_manual_{impl}")


def split_inputs_sequence_dim(inputs, rank=None, degree=None, axis=1,
                              zigzag=False):
    """Fleet's ``split_inputs_sequence_dim``: pre-shard host batches along
    the sequence dim for the sep group. In single-process SPMD the whole
    (optionally zigzag-reordered) sequence is returned and the mesh
    sharding does the split; with an explicit rank the local slice is cut
    out (multi-process layout)."""
    if degree is None:
        _, degree = _sep_axis()
    if degree <= 1:
        return inputs

    def one(t):
        arr = t.jax() if isinstance(t, Tensor) else jnp.asarray(t)
        if arr.shape[axis] % degree:
            raise ValueError(
                f"sequence length {arr.shape[axis]} not divisible by sep "
                f"degree {degree}")
        if zigzag:
            arr = ra.zigzag_reorder(arr, degree, axis=axis)
        if rank is not None:
            per = arr.shape[axis] // degree
            sl = [slice(None)] * arr.ndim
            sl[axis] = slice(rank * per, (rank + 1) * per)
            arr = arr[tuple(sl)]
        return Tensor(arr) if isinstance(t, Tensor) else arr

    if isinstance(inputs, (list, tuple)):
        return type(inputs)(one(t) for t in inputs)
    if isinstance(inputs, dict):
        return {k2: one(v2) for k2, v2 in inputs.items()}
    return one(inputs)


def gather_outputs_sequence_dim(outputs, degree=None, axis=1, zigzag=False):
    """Undo zigzag reordering on a full (gathered) sequence tensor."""
    if degree is None:
        _, degree = _sep_axis()
    if degree <= 1 or not zigzag:
        return outputs
    arr = outputs.jax() if isinstance(outputs, Tensor) else \
        jnp.asarray(outputs)
    arr = ra.zigzag_restore(arr, degree, axis=axis)
    return Tensor(arr) if isinstance(outputs, Tensor) else arr


def sep_positions(seq_len, degree=None, zigzag=False):
    """Global RoPE position ids for a sep-sharded (optionally zigzag)
    sequence, as a host numpy array of shape [seq_len]."""
    import numpy as np
    if degree is None:
        _, degree = _sep_axis()
    if zigzag and degree > 1:
        return ra.zigzag_positions(seq_len, degree)
    return np.arange(seq_len, dtype=np.int32)
