"""PipelineParallel runtime — fleet ``pipeline_parallel.py`` parity
(UNVERIFIED).

Reference: 1F1B/interleaved schedules over NCCL p2p between stage processes
(SURVEY.md §3.4). TPU-native round-1 engine: microbatched GPipe-style
schedule executed as python-driven microbatch loop with gradient
accumulation. With pp_degree==1 (or single process) every stage runs
locally — this is the loss-parity reference. The shard_map+ppermute
multi-stage compiled schedule lands in the pipeline module
(paddle_tpu/distributed/pipeline.py) and is used when a mesh 'pipe' axis
has >1 devices."""

from __future__ import annotations

from ....framework.core import Tensor
from ....ops.manipulation import split as split_op

__all__ = ["PipelineParallel"]


class PipelineParallel:
    def __init__(self, layers, hcg, accumulate_steps=1, strategy=None):
        self._layers = layers
        self._hcg = hcg
        self.accumulate_steps = max(int(accumulate_steps), 1)

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Split into microbatches, accumulate grads, one optimizer step.
        Returns the mean loss (paddle semantics)."""
        inputs, labels = data
        n = self.accumulate_steps
        if n > 1:
            micro_x = split_op(inputs, n, axis=0)
            micro_y = split_op(labels, n, axis=0)
        else:
            micro_x, micro_y = [inputs], [labels]
        total = None
        for mx, my in zip(micro_x, micro_y):
            out = self._layers(mx)
            loss = self._layers._loss_fn(out, my)
            (loss / float(n)).backward()
            total = loss if total is None else total + loss
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / float(n)

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out
