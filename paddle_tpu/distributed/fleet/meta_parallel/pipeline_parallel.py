"""PipelineParallel runtime — fleet ``pipeline_parallel.py`` parity
(UNVERIFIED; reference mount empty).

Reference: FThenB/1F1B/interleaved schedules over NCCL p2p between stage
processes (SURVEY.md §3.4). TPU-native engine:

- pp_degree == 1: python-driven microbatch loop with gradient
  accumulation (the loss-parity oracle, and the eager-debug path — the
  role dygraph plays vs to_static in the reference).
- pp_degree > 1: ONE compiled program over the mesh's 'pipe' axis
  (``paddle_tpu.distributed.pipeline``): the PipelineLayer's layer list
  is decomposed into [prologue | uniform body | epilogue]; the body —
  the run of structurally-identical layers (transformer decoder stack) —
  is split into S stage groups whose weights are stacked [S, ...] and
  sharded over 'pipe'; prologue (embedding) and epilogue (norm/head/loss)
  run under plain GSPMD. Activations hop stages via ppermute inside a
  lax.scan (see pipeline.py for the schedule/bubble analysis). The
  backward pipeline is jax reverse-mode through that scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.core import (GradNode, Tensor, apply, current_tracking,
                                no_grad)
from ....framework import core as _core
from ....ops.manipulation import split as split_op

__all__ = ["PipelineParallel"]

#: strategy.pipeline_configs["schedule_mode"] -> engine kind.
#: 'FThenB' (default) = the compiled lax.scan pipeline with jax
#: reverse-mode backward; 'interleaved' (a.k.a. 'vpp') = the same scan
#: engine with V > 1 virtual chunks per device (Megatron virtual-pp:
#: round-robin chunk placement, 1/V bubble shrink — pipeline.py's
#: _pipeline_interleaved), V from
#: strategy.pipeline_configs["num_virtual_pipeline_stages"] or the
#: PipelineLayer's own num_virtual_pipeline_stages;
#: '1F1B' / 'ZB-H1' = the explicit-schedule tick engine in
#: distributed/zero_bubble.py (true warmup/steady/cooldown order, W-unit
#: bubble filling for ZB-H1).
_SCHEDULES = {
    "fthenb": "fthenb", "f-then-b": "fthenb", "f_then_b": "fthenb",
    "gpipe": "fthenb",
    "interleaved": "interleaved", "vpp": "interleaved",
    "interleaved-1f1b": "interleaved", "interleaved_1f1b": "interleaved",
    "1f1b": "1f1b", "zb_h1": "zb_h1", "zb-h1": "zb_h1", "zbh1": "zb_h1",
}

#: schedule kinds served by the compiled lax.scan engine (pipeline.py);
#: the others run the explicit tick machine (zero_bubble.py).
_SCAN_SCHEDULES = ("fthenb", "interleaved")


def _make_stage_fn(template, template_params):
    """Shape/dtype-preserving stage compute over ONE chunk's param leaves:
    rebind the template layers' params, run them, restore. Shared by the
    compiled FThenB body and the explicit-schedule engine."""
    def stage_fn(params_one, x):
        originals = [(p, p._data) for p in template_params]
        try:
            for p, a in zip(template_params, params_one):
                p._data = a
            t = Tensor(x)
            with no_grad():
                for l in template:
                    t = l(t)
            return t.jax() if isinstance(t, Tensor) else t
        finally:
            for p, a in originals:
                p._data = a
    return stage_fn


def _param_sig(layer):
    """Structural identity of a layer: class + param shapes/dtypes. The
    class matters — two layers with identical parameters but different
    forward() must not land in the same uniform body run."""
    return (type(layer).__name__,
            tuple((tuple(p.shape), str(p.dtype))
                  for p in layer.parameters()))


class PipelineParallel:
    def __init__(self, layers, hcg, accumulate_steps=1, strategy=None,
                 schedule_mode=None, num_virtual_pipeline_stages=None):
        self._layers = layers
        self._hcg = hcg
        self.accumulate_steps = max(int(accumulate_steps), 1)
        self._pp_degree = (hcg.get_pipe_parallel_world_size()
                           if hcg is not None else 1)
        if strategy is not None:
            if schedule_mode is None:
                schedule_mode = strategy.pipeline_configs.get(
                    "schedule_mode", "FThenB")
            if num_virtual_pipeline_stages is None:
                num_virtual_pipeline_stages = strategy.pipeline_configs.get(
                    "num_virtual_pipeline_stages")
        raw = str(schedule_mode or "FThenB")
        try:
            self._schedule = _SCHEDULES[raw.lower().strip()]
        except KeyError:
            raise ValueError(
                f"unknown pipeline schedule_mode {raw!r}; one of "
                f"{sorted(set(_SCHEDULES))}") from None
        # virtual-stage count: explicit arg / strategy override beats the
        # PipelineLayer's own construction-time value
        v_layer = max(int(getattr(layers, "_num_virtual", 1) or 1), 1)
        v_cfg = (max(int(num_virtual_pipeline_stages), 1)
                 if num_virtual_pipeline_stages is not None else None)
        if v_cfg is not None and v_cfg > 1 and v_layer > 1 and \
                v_cfg != v_layer:
            raise ValueError(
                f"num_virtual_pipeline_stages={v_cfg} conflicts with the "
                f"PipelineLayer's num_virtual_pipeline_stages={v_layer}")
        # an explicit config value wins (v_cfg=1 deliberately flattens a
        # V>1 layer back to S plain stages — the escape hatch the
        # explicit-schedule error below recommends)
        self._num_virtual = v_cfg if v_cfg is not None else v_layer
        if self._schedule == "interleaved" and self._num_virtual <= 1:
            raise ValueError(
                "schedule_mode='interleaved' needs virtual pipeline "
                "stages: set pipeline_configs['num_virtual_pipeline_"
                "stages'] > 1 (or build the PipelineLayer with "
                "num_virtual_pipeline_stages > 1)")
        self._compiled_plan = None
        if self._pp_degree > 1:
            self._compiled_plan = self._build_plan()
            if self._schedule not in _SCAN_SCHEDULES and \
                    self._compiled_plan["n_virtual"] > 1:
                raise ValueError(
                    "explicit schedules (1F1B/ZB-H1) do not support "
                    "virtual pipeline stages; use schedule_mode="
                    "'interleaved' or num_virtual_pipeline_stages=1")
            if self._schedule not in _SCAN_SCHEDULES and \
                    self._sep_axes() and self._sep_impl() == "ring":
                raise ValueError(
                    "ring context parallelism under the explicit "
                    "1F1B/ZB-H1 engines is not supported: the ring's "
                    "ppermute rotation scan sits inside the tick "
                    "machine's pipe-varying lax.switch, which breaks "
                    "the rotation (measured round 4: one rank's chunk "
                    "duplicated; round 5 re-probe: NaN loss — see "
                    "docs/ring_under_tick_engines.md). Use "
                    "sep_parallel='allgather' (gathered-K/V CP, "
                    "unbounded degree) or 'ulysses' (degree <= "
                    "num_heads) — both supported under every schedule "
                    "— or the scan schedules (FThenB/interleaved) "
                    "for ring")

    def _sep_impl(self):
        """The stage layers' sep attention impl ('ring' | 'ulysses' |
        'allgather'), or None — the single config walk both _sep_axes
        and the schedule validation derive from."""
        for l in self._layers.run_function:
            cfg = getattr(l, "cfg", None) or getattr(l, "config", None)
            impl = getattr(cfg, "sep_parallel", None) if cfg else None
            if impl is not None:
                return impl
        return None

    def _sep_axes(self):
        """('sep',) when this pipeline composes with an active context-
        parallel axis — i.e. the mesh's sep degree > 1 AND the stage
        layers actually run sep attention (their config carries
        sep_parallel). Empty tuple otherwise."""
        if self._hcg is None or \
                self._hcg.get_sep_parallel_world_size() <= 1:
            return ()
        if self._sep_impl() is not None:
            return (self._hcg.sep_axis_name,)
        return ()

    def _expert_axes(self):
        """('expert',) when the mesh's ep degree > 1 AND the stage
        layers contain MoE blocks — the pipeline region then binds the
        expert axis manually so MoELayer's all-to-all dispatch runs
        inside the compiled pipeline program (ep x pp)."""
        if self._hcg is None or \
                self._hcg.get_expert_parallel_world_size() <= 1:
            return ()
        from ....incubate.distributed.models.moe import MoELayer
        for l in self._layers.run_function:
            for m in l.sublayers(include_self=True):
                if isinstance(m, MoELayer):
                    return (self._hcg.ep_axis_name,)
        return ()

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    # ---- compiled-plan construction -------------------------------------

    def _build_plan(self):
        """Split run_function into prologue / uniform body / epilogue and
        group the body into S*V chunks of equal layer count (V > 1 =
        interleaved virtual stages; chunk c lives on device c % S)."""
        S = self._pp_degree
        V = self._num_virtual
        n_chunks = S * V
        layer_list = list(self._layers.run_function)
        sigs = [_param_sig(l) for l in layer_list]
        # longest contiguous run of identical non-empty signatures
        best = (0, 0)  # (start, length)
        i = 0
        while i < len(layer_list):
            if not sigs[i][1]:  # param-less layers can't anchor the body
                i += 1
                continue
            j = i
            while j < len(layer_list) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1]:
                best = (i, j - i)
            i = j
        start, length = best
        usable = (length // n_chunks) * n_chunks
        if usable < n_chunks:
            raise ValueError(
                f"pipeline compile: need a run of >= {n_chunks} "
                f"structurally identical layers to partition over "
                f"{S} stages x {V} virtual chunks; found {length}. "
                f"Adjust the PipelineLayer or pp_degree.")
        # keep trailing non-uniform layers in the epilogue; any uniform
        # surplus (length - usable) also joins the epilogue
        body = layer_list[start:start + usable]
        prologue = layer_list[:start]
        epilogue = layer_list[start + usable:]
        per_stage = usable // n_chunks
        groups = [body[g * per_stage:(g + 1) * per_stage]
                  for g in range(n_chunks)]
        group_params = [[p for l in grp for p in l.parameters()]
                        for grp in groups]
        n_leaves = len(group_params[0])
        for gp in group_params[1:]:
            assert len(gp) == n_leaves
        return {
            "prologue": prologue,
            "groups": groups,
            "epilogue": epilogue,
            "group_params": group_params,
            "n_leaves": n_leaves,
            "per_stage": per_stage,
            "n_virtual": V,
        }

    def _body_apply(self, h_micro):
        """Run the stacked body pipeline as ONE differentiable op:
        apply(fn, h_micro, *all_group_params)."""
        from ...pipeline import run_pipeline
        plan = self._compiled_plan
        S = self._pp_degree
        V = plan["n_virtual"]
        n_leaves = plan["n_leaves"]
        template = plan["groups"][0]
        template_params = [p for l in template for p in l.parameters()]
        mesh = self._hcg.global_mesh
        remat = "stage" if getattr(self._layers, "_recompute_interval", 0) \
            else None
        flat = [p for gp in plan["group_params"] for p in gp]

        def fn(hm, *leaves):
            if V == 1:
                stacked = tuple(
                    jnp.stack([leaves[g * n_leaves + i]
                               for g in range(S)])
                    for i in range(n_leaves))
            else:
                # [V, S, ...]: chunk c = v*S + d is device d's local
                # chunk v (round-robin placement — see pipeline.py)
                stacked = tuple(
                    jnp.stack([
                        jnp.stack([leaves[(v * S + d) * n_leaves + i]
                                   for d in range(S)])
                        for v in range(V)])
                    for i in range(n_leaves))

            sep = self._sep_axes()
            expert = self._expert_axes()
            extra = sep + expert
            x_spec = None
            param_specs = None
            from jax.sharding import PartitionSpec as P
            if sep:
                # h_micro is [M, b//M, S, H] — sequence dim 2 rides the
                # context axis through the manual region (activations
                # stay REPLICATED over 'expert'; MoELayer slices its
                # token shard internally)
                x_spec = P(None, None, sep[0])
            if expert:
                # keep expert-weight banks SHARDED over 'expert' through
                # the region (template leaves tagged by MoELayer) —
                # otherwise the boundary all-gathers every bank and
                # per-device weight memory scales with E instead of E/ep
                pipe_ax = self._hcg.pp_axis_name

                def leaf_spec(p):
                    shard = getattr(p, "_ep_shard_dim", None)
                    base = (pipe_ax,) if V == 1 else (None, pipe_ax)
                    if shard == 0:
                        return P(*base, expert[0])
                    return P(*base)

                param_specs = tuple(leaf_spec(p) for p in template_params)
            return run_pipeline(_make_stage_fn(template, template_params),
                                stacked, hm, mesh,
                                axis_name=self._hcg.pp_axis_name,
                                n_virtual=V, remat=remat,
                                extra_axes=extra, x_spec=x_spec,
                                param_specs=param_specs)

        return apply(fn, h_micro, *flat, name="pipeline_body")

    def _prologue_micro(self, inputs):
        """Run the prologue and reshape its output to [M, b//M, ...]."""
        plan = self._compiled_plan
        M = self.accumulate_steps
        h = inputs
        for l in plan["prologue"]:
            h = l(h)
        b = h.shape[0]
        if b % M:
            raise ValueError(f"batch {b} not divisible by "
                             f"accumulate_steps {M}")
        from ....ops.manipulation import reshape
        return reshape(h, [M, b // M] + list(h.shape[1:])), b

    def _forward_compiled(self, inputs):
        plan = self._compiled_plan
        h_micro, b = self._prologue_micro(inputs)
        from ....ops.manipulation import reshape
        out_micro = self._body_apply(h_micro)
        out = reshape(out_micro, [b] + list(out_micro.shape[2:]))
        for l in plan["epilogue"]:
            out = l(out)
        return out

    # ---- explicit-schedule engine (1F1B / ZB-H1) -------------------------

    def _engine_jit(self):
        """One jitted program: explicit-schedule engine + grad unstack.

        A single program matters beyond speed: slicing the pipe-sharded
        grad stacks eagerly would dispatch many small collective programs
        concurrently, which deadlocks XLA:CPU's rendezvous (and would
        serialize on TPU). Memoized per engine instance."""
        if getattr(self, "_engine_fn", None) is not None:
            return self._engine_fn
        from ...zero_bubble import run_pipeline_train
        plan = self._compiled_plan
        S = self._pp_degree
        n_leaves = plan["n_leaves"]
        template = plan["groups"][0]
        template_params = [p for l in template for p in l.parameters()]
        epi_layers = plan["epilogue"]
        epi_refs = [p for l in epi_layers for p in l.parameters()]
        mesh = self._hcg.global_mesh
        axis = self._hcg.pp_axis_name
        schedule = self._schedule
        loss_layer = self._layers._loss_fn
        stage_fn = _make_stage_fn(template, template_params)
        sep = self._sep_axes()
        expert = self._expert_axes()
        from jax.sharding import PartitionSpec as P
        x_spec = None
        if sep:
            # per-microbatch activations inside the engine are
            # [mb, S, H]; the stream is [M, mb, S, H] — seq dim 2
            x_spec = P(None, None, sep[0])
        param_specs = None
        if expert:
            # ep x pp under the tick engine: keep expert-weight banks
            # sharded over 'expert' through the manual region (same
            # leaf tagging as the scan engine's _body_apply); their
            # grads come back as local shards — the ep-aware reduction
            # (see zero_bubble.pipeline_train_spmd expert_axes note)
            def _leaf_spec(p):
                if getattr(p, "_ep_shard_dim", None) == 0:
                    return P(axis, expert[0])
                return P(axis)

            param_specs = tuple(_leaf_spec(p) for p in template_params)

        def epi_fn(y, tgt, epi_leaves):
            originals = [(p, p._data) for p in epi_refs]
            try:
                if sep:
                    from jax import lax as _lax
                    # the epilogue + shifted loss need the FULL
                    # sequence: gather the context-sharded hidden
                    # states (seq dim 1 per microbatch); the loss then
                    # computes identically on every sep rank, and the
                    # engine tail normalizes it back to invariance.
                    # COST: every sep rank runs the full epilogue
                    # (norm + vocab projection + CE) over the gathered
                    # sequence — sep_degree x redundant last-stage
                    # FLOPs. Generic-correct for ANY loss_fn; a
                    # loss-aware fast path (local-shard logits +
                    # offset labels + psum of partials) would need the
                    # shifted-CE structure, and the scan schedules
                    # remain the throughput path for 5D runs
                    y = _lax.all_gather(y, sep[0], axis=1, tiled=True)
                for p, a in zip(epi_refs, epi_leaves):
                    p._data = a
                t = Tensor(y)
                with no_grad():
                    for l in epi_layers:
                        t = l(t)
                    loss = loss_layer(t, Tensor(tgt))
                return loss.jax().astype(jnp.float32).reshape(())
            finally:
                for p, a in originals:
                    p._data = a

        def engine_call(body_leaves, hm, tgt_micro, epi_leaves):
            # stack [S, ...] inside the program so it fuses (and so no
            # eager per-leaf dispatch happens on the host each step)
            stacked = tuple(
                jnp.stack([body_leaves[g * n_leaves + i]
                           for g in range(S)])
                for i in range(n_leaves))
            loss, dp, _y, dx_micro, depi = run_pipeline_train(
                stage_fn, None, stacked, hm, tgt_micro, mesh,
                axis_name=axis, schedule=schedule,
                epi_fn=epi_fn, epi_params=epi_leaves,
                extra_axes=sep, x_spec=x_spec,
                param_specs=param_specs, expert_axes=expert)
            body_grads = tuple(dp[i][g] for g in range(S)
                               for i in range(n_leaves))
            return loss, body_grads, dx_micro, depi

        # Replicate every output at the jit boundary: params are
        # replicated, so grads must come back replicated too — otherwise
        # each eager optimizer update op would trigger its own resharding
        # collective (deadlock-prone on XLA:CPU, serialized on TPU).
        # Exception: expert-bank grads stay sharded over 'expert', same
        # as the banks themselves (sharded param + sharded grad keep the
        # optimizer update local to each ep rank).
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(mesh, PartitionSpec())

        def _grad_sh(p):
            if expert and getattr(p, "_ep_shard_dim", None) == 0:
                return NamedSharding(mesh, PartitionSpec(expert[0]))
            return repl

        out_sh = (repl,
                  tuple(_grad_sh(template_params[i])
                        for _g in range(S) for i in range(n_leaves)),
                  repl,
                  tuple(repl for _ in range(len(epi_refs))))
        self._engine_fn = jax.jit(engine_call, out_shardings=out_sh)
        # fixed once the plan exists; cached so the hot loop doesn't walk
        # every layer's parameters each step (ordering must match
        # engine_call's body_leaves[g*n_leaves+i] layout)
        self._engine_body_refs = [p for gp in plan["group_params"]
                                  for p in gp]
        self._engine_epi_refs = epi_refs
        return self._engine_fn

    def _explicit_loss(self, h_micro, labels):
        """Run the explicit tick engine (zero_bubble.py) as ONE tape op.

        The engine computes the loss AND every gradient in its forward
        pass (its backward IS the schedule); a manual GradNode hands the
        precomputed grads to the enclosing backward, scaled by the
        incoming cotangent — so prologue params still get their grads via
        dx_micro and paddle's loss.backward()/opt.step() flow unchanged."""
        engine = self._engine_jit()
        body_refs = self._engine_body_refs
        epi_refs = self._engine_epi_refs

        body_leaves = tuple(p._data for p in body_refs)
        epi_leaves = tuple(p._data for p in epi_refs)
        tgt = labels._data if isinstance(labels, Tensor) \
            else jnp.asarray(labels)
        M = h_micro.shape[0]
        tgt_micro = jnp.reshape(tgt, (M, tgt.shape[0] // M) + tgt.shape[1:])

        loss, body_grads, dx_micro, depi = engine(
            body_leaves, h_micro._data, tgt_micro, epi_leaves)

        # hand the precomputed grads to the tape
        parents = [h_micro] + body_refs + list(epi_refs)
        grads = [dx_micro] + list(body_grads) + list(depi)
        tr = current_tracking()
        if tr is not None:
            for p in parents[1:]:
                if p.persistable:
                    tr.record_read(p)
        needs = _core._grad_state.enabled and any(
            not p._stop_gradient for p in parents)
        loss_t = Tensor(loss, stop_gradient=not needs)
        if needs:
            pairs = [(p, g) for p, g in zip(parents, grads)
                     if not p._stop_gradient]
            node = GradNode(
                lambda ct: tuple(ct * g for _, g in pairs),
                [p for p, _ in pairs], 1, name="pipeline_explicit",
                out_avals=[(loss.shape, loss.dtype)])
            loss_t._node, loss_t._out_idx = node, 0
        return loss_t

    def _train_batch_explicit(self, inputs, labels, optimizer,
                              lr_scheduler=None, scaler=None):
        h_micro, _b = self._prologue_micro(inputs)
        loss = self._explicit_loss(h_micro, labels) / float(
            self.accumulate_steps)
        loss.backward()
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    # ---- train / eval ----------------------------------------------------

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Microbatch-accumulated step; one optimizer step. Returns the
        mean loss (paddle semantics)."""
        inputs, labels = data
        if self._compiled_plan is not None and \
                self._schedule not in _SCAN_SCHEDULES:
            return self._train_batch_explicit(inputs, labels, optimizer,
                                              lr_scheduler, scaler)
        if self._compiled_plan is not None:
            out = self._forward_compiled(inputs)
            loss = self._layers._loss_fn(out, labels)
            loss.backward()
            if scaler is not None:
                scaler.step(optimizer)
            else:
                optimizer.step()
            optimizer.clear_grad()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return loss
        n = self.accumulate_steps
        if n > 1:
            micro_x = split_op(inputs, n, axis=0)
            micro_y = split_op(labels, n, axis=0)
        else:
            micro_x, micro_y = [inputs], [labels]
        total = None
        for mx, my in zip(micro_x, micro_y):
            out = self._layers(mx)
            loss = self._layers._loss_fn(out, my)
            (loss / float(n)).backward()
            total = loss if total is None else total + loss
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / float(n)

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        if self._compiled_plan is not None:
            with no_grad():
                out = self._forward_compiled(inputs)
                if compute_loss and self._layers._loss_fn is not None:
                    return self._layers._loss_fn(out, labels)
                return out
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out
