"""PipelineParallel runtime — fleet ``pipeline_parallel.py`` parity
(UNVERIFIED; reference mount empty).

Reference: FThenB/1F1B/interleaved schedules over NCCL p2p between stage
processes (SURVEY.md §3.4). TPU-native engine:

- pp_degree == 1: python-driven microbatch loop with gradient
  accumulation (the loss-parity oracle, and the eager-debug path — the
  role dygraph plays vs to_static in the reference).
- pp_degree > 1: ONE compiled program over the mesh's 'pipe' axis
  (``paddle_tpu.distributed.pipeline``): the PipelineLayer's layer list
  is decomposed into [prologue | uniform body | epilogue]; the body —
  the run of structurally-identical layers (transformer decoder stack) —
  is split into S stage groups whose weights are stacked [S, ...] and
  sharded over 'pipe'; prologue (embedding) and epilogue (norm/head/loss)
  run under plain GSPMD. Activations hop stages via ppermute inside a
  lax.scan (see pipeline.py for the schedule/bubble analysis). The
  backward pipeline is jax reverse-mode through that scan.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.core import Tensor, apply, no_grad
from ....ops.manipulation import split as split_op

__all__ = ["PipelineParallel"]


def _param_sig(layer):
    """Structural identity of a layer: class + param shapes/dtypes. The
    class matters — two layers with identical parameters but different
    forward() must not land in the same uniform body run."""
    return (type(layer).__name__,
            tuple((tuple(p.shape), str(p.dtype))
                  for p in layer.parameters()))


class PipelineParallel:
    def __init__(self, layers, hcg, accumulate_steps=1, strategy=None):
        self._layers = layers
        self._hcg = hcg
        self.accumulate_steps = max(int(accumulate_steps), 1)
        self._pp_degree = (hcg.get_pipe_parallel_world_size()
                           if hcg is not None else 1)
        self._compiled_plan = None
        if self._pp_degree > 1:
            self._compiled_plan = self._build_plan()

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    # ---- compiled-plan construction -------------------------------------

    def _build_plan(self):
        """Split run_function into prologue / uniform body / epilogue and
        group the body into S*V chunks of equal layer count (V > 1 =
        interleaved virtual stages; chunk c lives on device c % S)."""
        S = self._pp_degree
        V = max(int(getattr(self._layers, "_num_virtual", 1) or 1), 1)
        n_chunks = S * V
        layer_list = list(self._layers.run_function)
        sigs = [_param_sig(l) for l in layer_list]
        # longest contiguous run of identical non-empty signatures
        best = (0, 0)  # (start, length)
        i = 0
        while i < len(layer_list):
            if not sigs[i][1]:  # param-less layers can't anchor the body
                i += 1
                continue
            j = i
            while j < len(layer_list) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[1]:
                best = (i, j - i)
            i = j
        start, length = best
        usable = (length // n_chunks) * n_chunks
        if usable < n_chunks:
            raise ValueError(
                f"pipeline compile: need a run of >= {n_chunks} "
                f"structurally identical layers to partition over "
                f"{S} stages x {V} virtual chunks; found {length}. "
                f"Adjust the PipelineLayer or pp_degree.")
        # keep trailing non-uniform layers in the epilogue; any uniform
        # surplus (length - usable) also joins the epilogue
        body = layer_list[start:start + usable]
        prologue = layer_list[:start]
        epilogue = layer_list[start + usable:]
        per_stage = usable // n_chunks
        groups = [body[g * per_stage:(g + 1) * per_stage]
                  for g in range(n_chunks)]
        group_params = [[p for l in grp for p in l.parameters()]
                        for grp in groups]
        n_leaves = len(group_params[0])
        for gp in group_params[1:]:
            assert len(gp) == n_leaves
        return {
            "prologue": prologue,
            "groups": groups,
            "epilogue": epilogue,
            "group_params": group_params,
            "n_leaves": n_leaves,
            "per_stage": per_stage,
            "n_virtual": V,
        }

    def _body_apply(self, h_micro):
        """Run the stacked body pipeline as ONE differentiable op:
        apply(fn, h_micro, *all_group_params)."""
        from ...pipeline import run_pipeline
        plan = self._compiled_plan
        S = self._pp_degree
        V = plan["n_virtual"]
        n_leaves = plan["n_leaves"]
        template = plan["groups"][0]
        template_params = [p for l in template for p in l.parameters()]
        mesh = self._hcg.global_mesh
        remat = "stage" if getattr(self._layers, "_recompute_interval", 0) \
            else None
        flat = [p for gp in plan["group_params"] for p in gp]

        def fn(hm, *leaves):
            if V == 1:
                stacked = tuple(
                    jnp.stack([leaves[g * n_leaves + i]
                               for g in range(S)])
                    for i in range(n_leaves))
            else:
                # [V, S, ...]: chunk c = v*S + d is device d's local
                # chunk v (round-robin placement — see pipeline.py)
                stacked = tuple(
                    jnp.stack([
                        jnp.stack([leaves[(v * S + d) * n_leaves + i]
                                   for d in range(S)])
                        for v in range(V)])
                    for i in range(n_leaves))

            def stage_fn(params_one, x):
                originals = [(p, p._data) for p in template_params]
                try:
                    for p, a in zip(template_params, params_one):
                        p._data = a
                    t = Tensor(x)
                    with no_grad():
                        for l in template:
                            t = l(t)
                    return t.jax() if isinstance(t, Tensor) else t
                finally:
                    for p, a in originals:
                        p._data = a

            return run_pipeline(stage_fn, stacked, hm, mesh,
                                axis_name=self._hcg.pp_axis_name,
                                n_virtual=V, remat=remat)

        return apply(fn, h_micro, *flat, name="pipeline_body")

    def _forward_compiled(self, inputs):
        plan = self._compiled_plan
        M = self.accumulate_steps
        h = inputs
        for l in plan["prologue"]:
            h = l(h)
        b = h.shape[0]
        if b % M:
            raise ValueError(f"batch {b} not divisible by "
                             f"accumulate_steps {M}")
        from ....ops.manipulation import reshape
        h_micro = reshape(h, [M, b // M] + list(h.shape[1:]))
        out_micro = self._body_apply(h_micro)
        out = reshape(out_micro, [b] + list(out_micro.shape[2:]))
        for l in plan["epilogue"]:
            out = l(out)
        return out

    # ---- train / eval ----------------------------------------------------

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Microbatch-accumulated step; one optimizer step. Returns the
        mean loss (paddle semantics)."""
        inputs, labels = data
        if self._compiled_plan is not None:
            out = self._forward_compiled(inputs)
            loss = self._layers._loss_fn(out, labels)
            loss.backward()
            if scaler is not None:
                scaler.step(optimizer)
            else:
                optimizer.step()
            optimizer.clear_grad()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return loss
        n = self.accumulate_steps
        if n > 1:
            micro_x = split_op(inputs, n, axis=0)
            micro_y = split_op(labels, n, axis=0)
        else:
            micro_x, micro_y = [inputs], [labels]
        total = None
        for mx, my in zip(micro_x, micro_y):
            out = self._layers(mx)
            loss = self._layers._loss_fn(out, my)
            (loss / float(n)).backward()
            total = loss if total is None else total + loss
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total / float(n)

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        if self._compiled_plan is not None:
            with no_grad():
                out = self._forward_compiled(inputs)
                if compute_loss and self._layers._loss_fn is not None:
                    return self._layers._loss_fn(out, labels)
                return out
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out
