"""PipelineLayer — fleet ``parallel_layers/pp_layers.py`` parity
(UNVERIFIED).

Describes a model as an ordered list of layer descs, partitioned into
pipeline stages. TPU-native execution: PipelineParallel runs the stages
inside one compiled program (lax.scan over microbatches + ppermute between
stage shards over the 'pipe' mesh axis) rather than NCCL p2p between
processes; with pp_degree==1 it runs the layers sequentially."""

from __future__ import annotations

from ....nn.layer.layers import Layer
from ....nn.layer.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc",
           "LocalSharedLayerDesc", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._descs = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval
        self._num_virtual = num_virtual_pipeline_stages or 1
        if num_stages is None:
            from ..base import fleet
            hcg = fleet._hcg
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = max(int(num_stages), 1)
        # build ALL layers (SPMD: every process holds the full program;
        # per-stage weights live on their pipe-mesh shard)
        self._shared: dict[str, Layer] = {}
        built = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(_SharedLayerRef(
                        self._shared[d.layer_name], d.forward_func))
                    continue
                layer = d.build_layer()
                self._shared[d.layer_name] = layer
                built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:  # callable (e.g. lambda reshape)
                built.append(_FnLayer(d))
        self.run_function = LayerList(built)
        self._segments = self._partition(len(built), self._num_stages)

    def _partition(self, n, stages):
        """Uniform / by-param segmentation → list of (start, end)."""
        if self._seg_method.startswith("layer:"):
            cls_name = self._seg_method.split(":", 1)[1]
            marks = [i for i, l in enumerate(self.run_function)
                     if type(l).__name__ == cls_name]
            if len(marks) >= stages:
                # distribute marked layers evenly
                per = len(marks) // stages
                bounds = [0]
                for s in range(1, stages):
                    bounds.append(marks[s * per])
                bounds.append(n)
                return [(bounds[i], bounds[i + 1]) for i in range(stages)]
        base = n // stages
        rem = n % stages
        segs, start = [], 0
        for s in range(stages):
            size = base + (1 if s < rem else 0)
            segs.append((start, start + size))
            start += size
        return segs

    def get_stage_layers(self, stage_id):
        s, e = self._segments[stage_id]
        return list(self.run_function)[s:e]

    @property
    def parameters_by_stage(self):
        return [[p for l in self.get_stage_layers(s)
                 for p in l.parameters()] for s in range(self._num_stages)]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class _SharedLayerRef(Layer):
    """Second occurrence of a SharedLayerDesc: reuses the first layer's
    weights (e.g. tied embedding/lm-head)."""

    def __init__(self, target: Layer, forward_func=None):
        super().__init__()
        self._target = [target]  # list to avoid sublayer registration
        self._forward_func = forward_func

    def forward(self, x):
        target = self._target[0]
        if self._forward_func is not None:
            return self._forward_func(target, x)
        return target(x)


class LocalSharedLayerDesc(SharedLayerDesc):
    """Reference ``LocalSharedLayerDesc``: a shared layer whose weight
    sync group is the LOCAL pipeline-stage replica group. In the
    compiled-pipeline design shared weights live once in the program
    (stacked stage weights reference one logical array), so local vs
    global sharing coincide; kept as a distinct type for parity."""
