"""Process topology — fleet ``topology.py`` parity (UNVERIFIED:
CommunicateTopology / HybridCommunicateGroup).

The reference computes each rank's (dp, sharding, pp, mp, sep) coordinate
and builds per-axis NCCL groups. Here the topology IS a named jax Mesh over
all devices; coordinates answer the same questions, and per-axis "groups"
are (axis_name, mesh) pairs usable both by GSPMD sharding constraints and by
shard_map collectives."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ..communication import Group, new_group

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "sep", "model", "expert"),
                 dims=(1, 1, 1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world = int(np.prod(self._dims))
        self._rank_arr = np.arange(self._world).reshape(self._dims)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        idx = tuple(kwargs[n] for n in self._names)
        return int(self._rank_arr[idx])

    def get_coord(self, rank):
        coords = np.unravel_index(rank, self._dims)
        return {n: int(c) for n, c in zip(self._names, coords)}

    def get_axis_list(self, axis_name, index):
        """All ranks whose `axis_name` coordinate == index."""
        ax = self._names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[ax] = index
        return self._rank_arr[tuple(sl)].reshape(-1).tolist()

    def get_comm_list(self, axis_name):
        """List of rank-groups along `axis_name` (one per other-coord)."""
        ax = self._names.index(axis_name)
        moved = np.moveaxis(self._rank_arr, ax, -1)
        return moved.reshape(-1, self._dims[ax]).tolist()


class HybridCommunicateGroup:
    """Reference-shaped API over the global mesh.

    Mesh axes use fleet's names: 'data' (dp), 'sharding', 'pipe' (pp),
    'model' (mp/tp), 'sep' (context), optional 'expert' folded into
    sharding dim for MoE models."""

    def __init__(self, topology: CommunicateTopology, mesh: Mesh = None):
        self._topo = topology
        self.global_rank = jax.process_index()
        self.global_mesh = mesh
        self.nranks = topology.world_size()
        coord = topology.get_coord(self._device_rank())
        self._dp_rank = coord.get("data", 0)
        self._sharding_rank = coord.get("sharding", 0)
        self._pp_rank = coord.get("pipe", 0)
        self._mp_rank = coord.get("model", 0)
        self._sep_rank = coord.get("sep", 0)
        self._ep_rank = coord.get("expert", 0)
        # axis names for collectives
        self.dp_axis_name = "data"
        self.sharding_axis_name = "sharding"
        self.pp_axis_name = "pipe"
        self.mp_axis_name = "model"
        self.sep_axis_name = "sep"
        self.ep_axis_name = "expert"
        self._groups = {
            name: new_group(
                ranks=topology.get_axis_list(
                    name, 0) if name in topology.get_hybrid_group_names()
                else [0],
                axis_name=name)
            for name in topology.get_hybrid_group_names()}

    def _device_rank(self):
        # single-process SPMD: the "rank" for coordinate queries is device 0
        # of this process; per-device coords only matter inside shard_map,
        # where lax.axis_index answers them.
        return 0

    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        if self.get_model_parallel_world_size() > 1 or \
                self.get_pipe_parallel_world_size() > 1:
            return "hybrid"
        if self.get_sharding_parallel_world_size() > 1:
            return "sharding"
        if self.get_data_parallel_world_size() > 1:
            return "data"
        return "single"

    # data parallel
    def get_data_parallel_rank(self):
        return self._dp_rank

    def get_data_parallel_world_size(self):
        return self._topo.get_dim("data")

    def get_data_parallel_group(self) -> Group:
        return self._groups["data"]

    def get_data_parallel_group_src_rank(self):
        return 0

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._mp_rank

    def get_model_parallel_world_size(self):
        return self._topo.get_dim("model")

    def get_model_parallel_group(self) -> Group:
        return self._groups["model"]

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    def get_stage_id(self):
        return self._pp_rank

    def get_pipe_parallel_rank(self):
        return self._pp_rank

    def get_pipe_parallel_world_size(self):
        return self._topo.get_dim("pipe")

    def get_pipe_parallel_group(self) -> Group:
        return self._groups["pipe"]

    def is_first_stage(self):
        return self._pp_rank == 0

    def is_last_stage(self):
        return self._pp_rank == self.get_pipe_parallel_world_size() - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._sharding_rank

    def get_sharding_parallel_world_size(self):
        return self._topo.get_dim("sharding")

    def get_sharding_parallel_group(self) -> Group:
        return self._groups["sharding"]

    def get_sharding_parallel_group_src_rank(self):
        return 0

    # sep (sequence/context)
    def get_sep_parallel_rank(self):
        return self._sep_rank

    def get_sep_parallel_world_size(self):
        return self._topo.get_dim("sep")

    def get_sep_parallel_group(self) -> Group:
        return self._groups["sep"]

    # expert parallel
    def get_expert_parallel_rank(self):
        return self._ep_rank

    def get_expert_parallel_world_size(self):
        try:
            return self._topo.get_dim("expert")
        except ValueError:
            return 1

    def get_expert_parallel_group(self) -> Group:
        return self._groups.get("expert")

    # checks
    def get_check_parallel_group(self, *a):
        return self._groups["model"]

    def get_rank_from_stage(self, stage_id, **kwargs):
        coord = self._topo.get_coord(self._device_rank())
        coord["pipe"] = stage_id
        coord.update(kwargs)
        return self._topo.get_rank(**coord)
