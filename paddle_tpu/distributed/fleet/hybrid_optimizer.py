"""HybridParallelOptimizer — fleet ``HybridParallelOptimizer`` parity
(UNVERIFIED).

Reference behavior (SURVEY.md §3.4 step 4): global-norm clip with norms
allreduced across mp/pp/sharding groups, then apply. TPU-native: when the
step runs compiled over the mesh, parameter shards are NamedSharding-ed and
grad norms computed on sharded arrays are already global (GSPMD inserts the
psum); eager single-process path is the plain clip."""

from __future__ import annotations

from ...optimizer.optimizer import Optimizer

__all__ = ["HybridParallelOptimizer"]


class HybridParallelOptimizer:
    def __init__(self, optimizer: Optimizer, hcg, strategy=None):
        self._inner = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def _parameter_list(self):
        return self._inner._parameter_list

    def step(self):
        self._inner.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program, parameters,
                                    no_grad_set)

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, state):
        self._inner.set_state_dict(state)
