"""fleet.utils — recompute + sequence-parallel helpers
(fleet/utils/ parity, UNVERIFIED)."""

from ...incubate.recompute import recompute

__all__ = ["recompute"]
