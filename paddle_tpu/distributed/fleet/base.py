"""fleet singleton + DistributedStrategy
(fleet/base/ parity, UNVERIFIED; DistributedStrategy is protobuf-backed in
the reference — here a plain dataclass-style config with the same knobs)."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["DistributedStrategy", "Fleet", "fleet", "init", "worker_num",
           "worker_index", "is_first_worker", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class DistributedStrategy:
    """Parallelism knobs (mirrors the reference's proto fields we support).

    hybrid_configs: dp_degree / mp_degree / pp_degree / sharding_degree /
    sep_degree — -1 means 'fill with remaining devices'."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": -1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "ep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        # schedule_mode: FThenB (compiled lax.scan pipeline, supports
        # interleaved virtual stages — the TPU-native default) | 1F1B |
        # ZB-H1 (explicit tick-table engines, zero_bubble.py)
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "schedule_mode": "FThenB"}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self.is_collective = is_collective


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    pass


class Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg: HybridCommunicateGroup | None = None
        self._topology: CommunicateTopology | None = None
        self._is_initialized = False

    # ---- init -----------------------------------------------------------

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        from ..env import init_parallel_env
        init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        n = jax.device_count()
        mp = max(int(hc.get("mp_degree", 1)), 1)
        pp = max(int(hc.get("pp_degree", 1)), 1)
        sh = max(int(hc.get("sharding_degree", 1)), 1)
        sep = max(int(hc.get("sep_degree", 1)), 1)
        ep = max(int(hc.get("ep_degree", 1)), 1)
        dp = int(hc.get("dp_degree", -1))
        if dp in (-1, 0):
            dp = max(n // (mp * pp * sh * sep * ep), 1)
        total = dp * sh * pp * sep * mp * ep
        if total > n:
            raise ValueError(
                f"hybrid degrees {dp}x{sh}x{pp}x{sep}x{mp}x{ep}={total} "
                f"exceed device count {n}")
        names = ("data", "sharding", "pipe", "sep", "model", "expert")
        dims = (dp, sh, pp, sep, mp, ep)
        self._topology = CommunicateTopology(names, dims)
        devices = np.asarray(jax.devices()[:total]).reshape(dims)
        mesh = Mesh(devices, names)
        self._hcg = HybridCommunicateGroup(self._topology, mesh)
        self._is_initialized = True
        # observable topology decision (profiler trace layer): which
        # hybrid mesh this process actually runs — the first thing to
        # check when a parallel run is slower than expected
        from ...profiler.trace import log_perf_event
        log_perf_event(
            "fleet/init",
            f"hybrid mesh dp{dp} x sharding{sh} x pp{pp} x sep{sep} "
            f"x mp{mp} x ep{ep} over {total}/{n} devices "
            f"({devices.flat[0].platform})")
        return self

    def is_first_worker(self):
        return jax.process_index() == 0

    def worker_index(self):
        return jax.process_index()

    def worker_num(self):
        return jax.process_count()

    def get_hybrid_communicate_group(self) -> HybridCommunicateGroup:
        return self._hcg

    @property
    def strategy(self):
        return self._strategy

    # ---- model / optimizer wrapping -------------------------------------

    def distributed_model(self, model):
        """Wrap for hybrid parallelism.

        GSPMD-first: TP layers already carry weight shardings; pipeline
        models (PipelineLayer) get the pipeline engine; plain models get
        data-parallel semantics (batch sharded over 'data', grads psum'd by
        GSPMD when compiled)."""
        if self._hcg is None:
            self.init()
        from .meta_parallel import PipelineLayer, PipelineParallel
        if isinstance(model, PipelineLayer) and \
                self._hcg.get_pipe_parallel_world_size() > 1:
            if self._strategy is not None and self._strategy.amp:
                import warnings
                warnings.warn(
                    "strategy.amp is not applied to pipeline models: the "
                    "compiled pipeline engine owns the program. Cast the "
                    "model (model.to(dtype='bfloat16')) or use auto_cast "
                    "inside the loss/layers instead.", UserWarning)
            accum = 1
            if self._strategy is not None:
                accum = self._strategy.pipeline_configs.get(
                    "accumulate_steps", 1)
            return PipelineParallel(model, self._hcg, accum,
                                    strategy=self._strategy)
        if self._strategy is not None and self._strategy.amp:
            # the reference's AMP meta-optimizer rewrites the program;
            # here the same contract is an auto_cast-wrapped forward
            return _AmpModelWrapper(model, self._strategy.amp_configs)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        from .hybrid_optimizer import HybridParallelOptimizer
        if self._hcg is None:
            self.init()
        sharding_degree = self._hcg.get_sharding_parallel_world_size()
        if sharding_degree > 1:
            from .sharding import DygraphShardingOptimizer
            optimizer = DygraphShardingOptimizer(optimizer, self._hcg)
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       self._strategy)

    # parity helpers used by trainers
    @property
    def util(self):
        return _util_singleton

    def barrier_worker(self):
        from ..communication import barrier
        barrier()

    def stop_worker(self):
        pass


def _make_amp_wrapper_cls():
    from ...nn.layer.layers import Layer

    class _AmpModelWrapper(Layer):
        """fleet AMP meta-optimizer role: run the wrapped model's forward
        under ``amp.auto_cast`` with the strategy's amp_configs. A real
        Layer (the model registers as a sublayer) so isinstance-gated
        paths — jit.save parameters, to_static Layer handling,
        state_dict — all see through it."""

        def __init__(self, model, amp_configs):
            super().__init__()
            self.model = model        # registered sublayer
            cfg = dict(amp_configs or {})
            self._amp_kw = {
                "level": cfg.get("level", "O1"),
                "dtype": cfg.get("dtype", "bfloat16"),
                "custom_white_list": cfg.get("custom_white_list"),
                "custom_black_list": cfg.get("custom_black_list"),
            }

        def forward(self, *args, **kwargs):
            from ...amp import auto_cast
            with auto_cast(True, **self._amp_kw):
                return self.model(*args, **kwargs)

        def __getattr__(self, name):
            try:
                return super().__getattr__(name)
            except AttributeError:
                return getattr(self.__dict__["_sub_layers"]["model"],
                               name)

    return _AmpModelWrapper


def _AmpModelWrapper(model, amp_configs):
    return _make_amp_wrapper_cls()(model, amp_configs)


fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None):
    return fleet.init(role_maker, is_collective, strategy)


def worker_num():
    return fleet.worker_num()


def worker_index():
    return fleet.worker_index()


def is_first_worker():
    return fleet.is_first_worker()


class UtilBase:
    """``fleet.util`` — host-side collective/file utilities (upstream
    fleet/base/util_factory.py, UNVERIFIED). Collectives are the
    control-plane object collectives (Gloo role); file helpers shard a
    file list across workers the way PS data loaders do."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from ..communication import all_gather_object
        if mode not in ("sum", "min", "max"):  # before the collective
            raise ValueError(f"util.all_reduce: unknown mode {mode!r}")
        parts: list = []
        all_gather_object(parts, input)
        return getattr(np.asarray(parts), mode)(0)

    def barrier(self, comm_world="worker"):
        from ..communication import barrier as _barrier
        _barrier()

    def all_gather(self, input, comm_world="worker"):
        from ..communication import all_gather_object
        out: list = []
        all_gather_object(out, input)
        return out

    def get_file_shard(self, files):
        """Split a file list contiguously across workers (upstream
        contract: earlier workers get the remainder)."""
        from ..env import get_rank, get_world_size
        n, rank = get_world_size(), get_rank()
        total = len(files)
        base, rem = divmod(total, n)
        start = rank * base + min(rank, rem)
        return list(files[start:start + base + (1 if rank < rem else 0)])

    def print_on_rank(self, message, rank_id=0):
        from ..env import get_rank
        if get_rank() == rank_id:
            print(message)


_util_singleton = UtilBase()
